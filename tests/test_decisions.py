"""Decision-provenance flight recorder (ISSUE 4 tentpole).

The contracts under test:
  (1) the winner's per-policy contributions SUM: Σ weight·norm equals the
      recorded selectHost total, exactly, for every placed create;
  (2) decision records are bit-identical across the flat, blocked,
      sequential, and shard_map engines (INVARIANT_FIELDS — `block` is
      the documented engine-specific slot, like the counters' rebuilds);
  (3) the stream is continuous across checkpoint kill/resume and across
      fault segmentation;
  (4) the JSONL persistence round-trips under the digest discipline
      (torn/edited files fail loudly);
  (5) `explain`/`diff` produce deterministic golden output on an openb
      prefix, and `diff` finds a deterministic first-divergence event
      between FGD and BestFit (the acceptance criterion).

Compile-heavy cases (4-engine invariance, shard top-K collective,
kill/resume, openb goldens) are slow-marked for the tier-1 time budget
and run under `make resume-smoke` / plain pytest.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import random_cluster, random_pods
from tpusim.io.trace import NodeRow, PodRow, pods_to_specs
from tpusim.obs.decisions import (
    DECISION_TOPK,
    DecisionLog,
    DecisionRecord,
    INVARIANT_FIELDS,
    decision_rows,
    divergence_histogram,
    first_divergence,
    format_diff,
    format_explain,
    read_decisions,
    run_diff,
    write_decisions,
)
from tpusim.policies import make_policy
from tpusim.sim.driver import Simulator, SimulatorConfig
from tpusim.sim.engine import EV_CREATE, EV_DELETE, make_replay
from tpusim.sim.table_engine import build_pod_types, make_table_replay

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WEIGHTS = (1000, 500)  # two-policy config: the sum check must be non-trivial


def _mixed_events(num_pods, rng):
    kinds, idxs, seen = [], [], set()
    for i in range(num_pods):
        kinds.append(EV_CREATE)
        idxs.append(i)
        if rng.random() < 0.3 and i > 0:
            victim = int(rng.integers(0, i + 1))
            if victim not in seen:
                seen.add(victim)
                kinds.append(EV_DELETE)
                idxs.append(victim)
    return jnp.asarray(kinds, jnp.int32), jnp.asarray(idxs, jnp.int32)


def _driver_inputs():
    rng = np.random.default_rng(31)
    nodes = [
        NodeRow(f"n{i}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], 12))
    ]
    pods = [
        PodRow(f"p{i}", int(rng.choice([1000, 4000])), 1024,
               int(rng.choice([0, 1])), 500)
        for i in range(30)
    ]
    return nodes, pods


def _replay(sim, pods):
    specs = pods_to_specs(pods)
    return sim.run_events(
        sim.init_state, specs, jnp.zeros(len(pods), jnp.int32),
        jnp.arange(len(pods), dtype=jnp.int32), jax.random.PRNGKey(2),
    )


def _run_driver(nodes, pods, every=0, ckdir="", seed=42):
    sim = Simulator(nodes, SimulatorConfig(
        policies=(("FGDScore", WEIGHTS[0]), ("BestFitScore", WEIGHTS[1])),
        gpu_sel_method="FGDScore", report_per_event=False,
        checkpoint_every=every, checkpoint_dir=ckdir, seed=seed,
        record_decisions=True,
    ))
    sim.set_workload_pods(pods)
    sim.set_typical_pods()
    return sim, _replay(sim, pods)


def _assert_records_equal(a, b, fields=DecisionRecord._fields):
    for f in fields:
        assert np.array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        ), f


# ---------------------------------------------------------------------------
# tier-1: one small driver replay pins the record semantics end to end
# ---------------------------------------------------------------------------


def test_winner_contributions_sum_and_consistency():
    """Through the driver (table engine): Σ weight·norm == recorded
    total for every placed create; topk entry 0 IS the committed winner
    with its total; failed creates record -1/0; the stream is
    bit-deterministic across two same-seed runs."""
    nodes, pods = _driver_inputs()
    sim, r1 = _run_driver(nodes, pods)
    # second same-seed replay through the SAME sim reuses the compiled
    # engine (tier-1 time budget); cross-PROCESS byte-identity of the
    # stream is pinned by the slow openb golden
    r2 = _replay(sim, pods)
    assert r1.decisions is not None
    d = jax.tree.map(np.asarray, r1.decisions)
    _assert_records_equal(d, jax.tree.map(np.asarray, r2.decisions))

    node = np.asarray(d.node)
    total = np.asarray(d.total)
    norm = np.asarray(d.norm)
    w = np.asarray(WEIGHTS)
    placed = node >= 0  # all events here are creates
    assert placed.any()
    # (1) the acceptance sum: per-policy weighted contributions == total
    assert np.array_equal((norm @ w)[placed], total[placed])
    # winner consistency with the replay telemetry + the topk head
    assert np.array_equal(node, np.asarray(r1.event_node))
    assert np.array_equal(np.asarray(d.topk_node)[placed, 0], node[placed])
    assert np.array_equal(np.asarray(d.topk_total)[placed, 0], total[placed])
    assert (np.asarray(d.feasible)[placed] > 0).all()
    # runner-up ordering: lexicographic (total desc, rank asc), no dups
    tkn = np.asarray(d.topk_node)
    tkt = np.asarray(d.topk_total)
    tkr = np.asarray(d.topk_rank)
    for e in np.flatnonzero(placed):
        valid = tkn[e] >= 0
        ns, ts, rs = tkn[e][valid], tkt[e][valid], tkr[e][valid]
        assert len(set(ns.tolist())) == len(ns)
        for j in range(len(ns) - 1):
            assert (ts[j] > ts[j + 1]) or (
                ts[j] == ts[j + 1] and rs[j] < rs[j + 1]
            )
    # failed creates (if any) carry the inert sentinels
    for e in np.flatnonzero(~placed):
        assert total[e] == 0 and (norm[e] == 0).all()
        assert (tkn[e] >= -1).all()


def test_driver_run_populates_decision_log(tmp_path):
    """Simulator.run() surfaces SimulateResult.decisions as a DecisionLog
    whose JSONL write/read round-trips under the digest discipline."""
    nodes, pods = _driver_inputs()
    sim = Simulator(nodes, SimulatorConfig(
        policies=(("FGDScore", WEIGHTS[0]), ("BestFitScore", WEIGHTS[1])),
        gpu_sel_method="FGDScore", report_per_event=False, seed=42,
        record_decisions=True,
    ))
    sim.set_workload_pods(pods)
    res = sim.run()
    log = res.decisions
    assert isinstance(log, DecisionLog)
    e = np.asarray(log.ev_kind).shape[0]
    assert np.asarray(log.records.node).shape[0] == e == res.events

    names = [p.name for p in res.pods]
    path = str(tmp_path / "run.jsonl")
    write_decisions(path, log, policies=list(sim.cfg.policies),
                    meta={"seed": 42}, pod_names=names)
    header, rows = read_decisions(path)
    assert header["topk"] == DECISION_TOPK
    assert header["policies"] == [["FGDScore", 1000], ["BestFitScore", 500]]
    assert rows == decision_rows(log, names)
    # explain at the first placed create reproduces the recorded total
    ev = next(r["e"] for r in rows if r["kind"] == 0 and r["node"] >= 0)
    text = format_explain(header, rows, ev)
    assert f"== recorded total {rows[ev]['total']}" in text
    # a torn/edited payload fails loudly (digest discipline)
    lines = open(path).read().splitlines()
    lines[1] = lines[1].replace(
        f'"node":{rows[0]["node"]}', f'"node":{rows[0]["node"] + 1}', 1
    )
    tam = str(tmp_path / "tampered.jsonl")
    open(tam, "w").write("\n".join(lines) + "\n")
    with pytest.raises(ValueError, match="digest mismatch"):
        read_decisions(tam)


# ---------------------------------------------------------------------------
# tier-1: host-only diff/explain logic
# ---------------------------------------------------------------------------


def _synthetic_rows(nodes_seq):
    return [
        {
            "e": i, "kind": 0, "pod": i, "node": int(n), "total": 10 * i,
            "raw": [1], "norm": [1],
            "topk": [[int(n), 10 * i, 0], [-1, 0, -1], [-1, 0, -1]],
            "feasible": 3, "block": -1, "name": f"p{i}",
        }
        for i, n in enumerate(nodes_seq)
    ]


def test_first_divergence_and_histogram():
    a = _synthetic_rows([1, 2, 3, 4, 5, 6, 7, 8])
    b = _synthetic_rows([1, 2, 9, 4, 5, 9, 7, 9])
    first = first_divergence(a, b)
    assert first["event"] == 2
    assert first["a"]["node"] == 3 and first["b"]["node"] == 9
    hist = divergence_histogram(a, b, buckets=4)
    assert hist["events"] == 8 and hist["diverged"] == 3
    assert hist["counts"] == [0, 1, 1, 1]  # events 2, 5, 7 / width 2
    assert hist["first"] == 2 and hist["last"] == 7
    assert first_divergence(a, a) is None
    text = format_diff({"policies": [["X", 1]]}, a,
                       {"policies": [["Y", 1]]}, b)
    assert "first divergence at event 2" in text
    assert "3 diverged placements" in text
    # identical runs: the no-divergence branch
    assert "no divergence" in format_diff(
        {"policies": [["X", 1]]}, a, {"policies": [["X", 1]]}, a
    )


def test_run_diff_rejects_mismatched_traces():
    """run_diff (the `tpusim diff` / analysis entry) errors loudly when
    the two files describe different traces instead of reporting a bogus
    divergence — and agrees with the piecewise helpers when they match."""
    a = _synthetic_rows([1, 2, 3, 4])
    b = _synthetic_rows([1, 2, 9, 4])
    d = run_diff({"policies": [["X", 1]]}, a, {"policies": [["Y", 1]]}, b)
    assert d["first"] == first_divergence(a, b)
    assert d["histogram"] == divergence_histogram(a, b)
    assert "first divergence at event 2" in d["text"]
    # same trace, shorter run: comparable on the overlap
    assert run_diff({}, a, {}, a[:2])["first"] is None
    # different pod stream -> not comparable
    c = _synthetic_rows([1, 2, 9, 4])
    c[1]["pod"] = 7
    with pytest.raises(ValueError, match="not comparable"):
        run_diff({}, a, {}, c)
    # different event kinds -> not comparable
    k = _synthetic_rows([1, 2, 9, 4])
    k[0]["kind"] = 1
    with pytest.raises(ValueError, match="different traces"):
        run_diff({}, a, {}, k)
    # same (kind, pod) indices but different pod NAMES -> not comparable
    # (unrelated traces both open with 'create pod 0')
    m = _synthetic_rows([1, 2, 9, 4])
    m[0]["name"] = "other/pod-0"
    with pytest.raises(ValueError, match="not comparable"):
        run_diff({}, a, {}, m)


def test_explain_non_create_and_unschedulable():
    rows = _synthetic_rows([5])
    rows.append({**rows[0], "e": 1, "kind": 1})
    rows.append({**rows[0], "e": 2, "node": -1, "total": 0, "feasible": 0,
                 "topk": [[-1, 0, -1]] * 3})
    header = {"policies": [["FGDScore", 1000]]}
    assert "no scheduling decision" in format_explain(header, rows, 1)
    assert "unschedulable" in format_explain(header, rows, 2)
    with pytest.raises(ValueError, match="out of range"):
        format_explain(header, rows, 99)
    # a file whose norm/weights do not reproduce the recorded total is
    # unusable input (exit 2 via cmd_explain), not a quietly-annotated
    # table: here weight 1000 * norm 1 != total 0
    with pytest.raises(ValueError, match="inconsistent"):
        format_explain(header, rows, 0)
    rows[0]["total"] = 1000  # consistent again -> the happy table
    assert "== recorded total 1000" in format_explain(header, rows, 0)


# ---------------------------------------------------------------------------
# slow lane: cross-engine invariance, kill/resume, faults, openb goldens
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_decisions_engine_invariant():
    """The same create/delete mix yields bit-identical decision records
    (INVARIANT_FIELDS) on the flat, blocked, sequential, and shard_map
    engines; the blocked path additionally records a valid winning block
    id and the rest record -1 (the documented engine-specific slot).
    slow-marked: compiles four engines incl. the shard top-K collective
    merge; runs under `make resume-smoke` / plain pytest."""
    from tpusim.parallel import make_mesh, pad_nodes, shard_state
    from tpusim.parallel.shard_engine import make_shardmap_table_replay

    rng = np.random.default_rng(7)
    state, tp = random_cluster(rng, num_nodes=24)
    pods = random_pods(rng, num_pods=40)
    ev_kind, ev_pod = _mixed_events(40, rng)
    policies = [(make_policy("FGDScore"), WEIGHTS[0]),
                (make_policy("BestFitScore"), WEIGHTS[1])]
    key = jax.random.PRNGKey(3)
    rank = jnp.asarray(rng.permutation(24).astype(np.int32))
    types = build_pod_types(pods)

    flat = make_table_replay(policies, gpu_sel="FGDScore", block_size=-1,
                             decisions=True)(
        state, pods, types, ev_kind, ev_pod, tp, key, rank
    )
    blocked = make_table_replay(policies, gpu_sel="FGDScore", block_size=8,
                                decisions=True)(
        state, pods, types, ev_kind, ev_pod, tp, key, rank
    )
    seq = make_replay(policies, gpu_sel="FGDScore", report=False,
                      decisions=True)(
        state, pods, ev_kind, ev_pod, tp, key, rank
    )
    mesh = make_mesh(4)
    st_p, rank_p = pad_nodes(state, rank, 4)
    shard = make_shardmap_table_replay(policies, mesh, gpu_sel="FGDScore",
                                       decisions=True)(
        shard_state(st_p, mesh), pods, types, ev_kind, ev_pod, tp, key,
        rank_p,
    )

    ref = flat.decisions
    for out in (blocked, seq, shard):
        assert np.array_equal(
            np.asarray(out.placed_node), np.asarray(flat.placed_node)
        )
        _assert_records_equal(ref, out.decisions, INVARIANT_FIELDS)
    # decision recording must not perturb the trajectory
    base = make_table_replay(policies, gpu_sel="FGDScore", block_size=-1)(
        state, pods, types, ev_kind, ev_pod, tp, key, rank
    )
    assert base.decisions is None
    assert np.array_equal(
        np.asarray(base.placed_node), np.asarray(flat.placed_node)
    )
    # block: valid on the blocked engine's placed creates, -1 on flat
    node = np.asarray(ref.node)
    placed = node >= 0
    assert (np.asarray(blocked.decisions.block)[placed] >= 0).all()
    assert (np.asarray(ref.block) == -1).all()


@pytest.mark.slow
def test_decisions_shard_blocked_local_invariant():
    """The shard engine's BLOCKED local select path (none-normalize
    config + block_size) records the same invariant fields as the flat
    and single-device blocked engines — including with local pad columns
    present (nloc not a multiple of B), whose synthetic global ids
    overlap the next shard's range but are infeasible and must never
    enter the top-K. slow-marked: compiles three engines incl. the
    shard top-K collective."""
    from tpusim.parallel import make_mesh, pad_nodes, shard_state
    from tpusim.parallel.shard_engine import make_shardmap_table_replay

    rng = np.random.default_rng(11)
    state, tp = random_cluster(rng, num_nodes=28)  # nloc 7, bsz 4 -> pads
    pods = random_pods(rng, num_pods=40)
    ev_kind, ev_pod = _mixed_events(40, rng)
    # both normalize == "none": the shard blocked-local gate
    policies = [(make_policy("FGDScore"), WEIGHTS[0]),
                (make_policy("GpuPackingScore"), WEIGHTS[1])]
    key = jax.random.PRNGKey(5)
    rank = jnp.asarray(rng.permutation(28).astype(np.int32))
    types = build_pod_types(pods)

    flat = make_table_replay(policies, gpu_sel="FGDScore", block_size=-1,
                             decisions=True)(
        state, pods, types, ev_kind, ev_pod, tp, key, rank
    )
    blocked = make_table_replay(policies, gpu_sel="FGDScore", block_size=4,
                                decisions=True)(
        state, pods, types, ev_kind, ev_pod, tp, key, rank
    )
    mesh = make_mesh(4)
    st_p, rank_p = pad_nodes(state, rank, 4)
    shard = make_shardmap_table_replay(
        policies, mesh, gpu_sel="FGDScore", block_size=4, decisions=True
    )(shard_state(st_p, mesh), pods, types, ev_kind, ev_pod, tp, key,
      rank_p)

    for out in (blocked, shard):
        assert np.array_equal(
            np.asarray(out.placed_node), np.asarray(flat.placed_node)
        )
        _assert_records_equal(flat.decisions, out.decisions,
                              INVARIANT_FIELDS)
    # both blocked selects name a winning block on placed creates; no
    # top-K entry may name a node outside the real cluster (pad columns)
    node = np.asarray(flat.decisions.node)
    placed = node >= 0
    assert placed.any()
    for out in (blocked, shard):
        assert (np.asarray(out.decisions.block)[placed] >= 0).all()
        tkn = np.asarray(out.decisions.topk_node)
        assert (tkn < 28).all() and (tkn >= -1).all()


@pytest.mark.slow
def test_decisions_survive_kill_resume(tmp_path):
    """The decision stream rides the checkpoint beside event_node/
    event_dev: a killed-and-resumed chunked run reproduces the
    uninterrupted run's stream bit-identically (nothing double- or
    under-recorded). slow-marked: compiles the chunked engine variants;
    runs under `make resume-smoke` / plain pytest."""
    import tpusim.io.storage as storage

    nodes, pods = _driver_inputs()
    _, r0 = _run_driver(nodes, pods)
    d0 = jax.tree.map(np.asarray, r0.decisions)

    # chunked-but-uninterrupted first: segmentation alone must be inert
    _, r1 = _run_driver(nodes, pods, every=10, ckdir=str(tmp_path))
    _assert_records_equal(d0, r1.decisions)

    real_save = storage.save_checkpoint

    def killing_save(*a, **k):
        real_save(*a, **k)
        raise KeyboardInterrupt("simulated preemption")

    storage.save_checkpoint = killing_save
    try:
        with pytest.raises(KeyboardInterrupt):
            _run_driver(nodes, pods, every=10, ckdir=str(tmp_path))
    finally:
        storage.save_checkpoint = real_save
    assert os.listdir(tmp_path)

    sim, r2 = _run_driver(nodes, pods, every=10, ckdir=str(tmp_path))
    assert any("[Checkpoint] resumed replay" in l for l in sim.log.lines)
    _assert_records_equal(d0, r2.decisions)


@pytest.mark.slow
def test_decisions_fault_segment_continuity():
    """Fault segmentation concatenates the per-segment streams: the
    pre-fault prefix is bit-identical to an unfaulted run's, and the
    whole stream is reproducible under the same fault schedule.
    slow-marked with the other fault-suite compile costs; runs under
    `make resume-smoke` / plain pytest."""
    from tpusim.sim.engine import EV_NODE_FAIL
    from tpusim.sim.faults import FaultEvent

    nodes, pods = _driver_inputs()

    def fault_run(faults):
        sim = Simulator(nodes, SimulatorConfig(
            policies=(("FGDScore", WEIGHTS[0]),
                      ("BestFitScore", WEIGHTS[1])),
            gpu_sel_method="FGDScore", report_per_event=False, seed=42,
            record_decisions=True,
        ))
        sim.set_workload_pods(pods)
        return sim.schedule_pods_with_faults(pods, faults=faults)

    base = fault_run([])
    faulted = fault_run([FaultEvent(pos=10, kind=EV_NODE_FAIL, node=0)])
    faulted2 = fault_run([FaultEvent(pos=10, kind=EV_NODE_FAIL, node=0)])
    assert base.decisions is not None and faulted.decisions is not None
    # continuity: the stream before the fault is the unfaulted stream
    for f in INVARIANT_FIELDS:
        assert np.array_equal(
            np.asarray(getattr(faulted.decisions.records, f))[:10],
            np.asarray(getattr(base.decisions.records, f))[:10],
        ), f
    # determinism: same schedule, same stream — retries included
    _assert_records_equal(faulted.decisions.records,
                          faulted2.decisions.records)
    assert np.asarray(faulted.decisions.ev_kind).shape[0] >= len(pods)


@pytest.mark.slow
def test_explain_diff_golden_openb(tmp_path):
    """The acceptance criterion on real trace data: FGD vs BestFit over
    an openb prefix yields a DETERMINISTIC first-divergence event from
    `tpusim diff`, and `tpusim explain` at that event shows a per-policy
    table whose weighted sum equals the recorded winner total. Golden:
    two same-seed runs produce byte-identical decision files and
    byte-identical explain/diff text."""
    from tpusim.io.trace import load_node_csv, load_pod_csv

    node_csv = os.path.join(REPO, "data/csv/openb_node_list_gpu_node.csv")
    pod_csv = os.path.join(REPO, "data/csv/openb_pod_list_default.csv")
    if not (os.path.isfile(node_csv) and os.path.isfile(pod_csv)):
        pytest.skip("openb traces not present")
    nodes = load_node_csv(node_csv)[:200]
    pods = load_pod_csv(pod_csv)[:120]

    def run(policy, gpu_sel, tag):
        sim = Simulator(nodes, SimulatorConfig(
            policies=((policy, 1000),), gpu_sel_method=gpu_sel,
            report_per_event=False, record_decisions=True, seed=42,
        ))
        sim.set_workload_pods(pods)
        res = sim.run()
        path = str(tmp_path / f"{tag}.jsonl")
        write_decisions(
            path, res.decisions, policies=list(sim.cfg.policies),
            meta=sim._telemetry_meta(), pod_names=[p.name for p in res.pods],
        )
        return path

    pa = run("FGDScore", "FGDScore", "fgd")
    pb = run("BestFitScore", "best", "bestfit")
    pa2 = run("FGDScore", "FGDScore", "fgd2")
    # golden: same-seed reruns are byte-identical files
    assert open(pa).read() == open(pa2).read()

    ha, ra = read_decisions(pa)
    hb, rb = read_decisions(pb)
    first = first_divergence(ra, rb)
    assert first is not None  # FGD and BestFit DO place differently
    # deterministic: recomputing from the re-run file finds the same event
    assert first_divergence(read_decisions(pa2)[1], rb)["event"] == \
        first["event"]

    ev = first["event"]
    text = format_explain(ha, ra, ev)
    r = ra[ev]
    contrib = sum(w * n for (_, w), n in zip(ha["policies"], r["norm"]))
    assert contrib == r["total"]
    assert f"== recorded total {r['total']}" in text
    text2 = format_explain(ha, read_decisions(pa2)[1], ev)
    assert text == text2
    dtext = format_diff(ha, ra, hb, rb, "A", "B")
    assert f"first divergence at event {ev}" in dtext
    hist = divergence_histogram(ra, rb)
    assert hist["diverged"] > 0 and sum(hist["counts"]) == hist["diverged"]

    # the CLI verbs drive the same surfaces (exit codes: diff(1) style)
    from tpusim.cli import main as cli_main

    assert cli_main(["explain", pa, "--event", str(ev)]) == 0
    assert cli_main(["diff", pa, pb]) == 1
    assert cli_main(["diff", pa, pa2]) == 0


def test_apply_decisions_out_and_explain(tmp_path):
    """`tpusim apply --decisions-out` writes the run's decision JSONL and
    `tpusim explain` reads it back — the full CLI loop on a 2-pod
    cluster (sequential engine: the small-batch path records too)."""
    import io

    import yaml

    from tpusim.apply import Applier, ApplyOptions

    cluster = tmp_path / "cluster"
    (cluster / "node").mkdir(parents=True)
    (cluster / "pod").mkdir(parents=True)
    (tmp_path / "cc.yaml").write_text(
        "apiVersion: simon/v1alpha1\nkind: Config\n"
        "metadata:\n  name: dec\n"
        f"spec:\n  cluster:\n    customConfig: {cluster}\n"
    )
    (cluster / "node" / "n0.yaml").write_text(yaml.dump({
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": "n0", "labels": {
            "alibabacloud.com/gpu-card-model": "V100M16"}},
        "status": {"allocatable": {
            "cpu": "64", "memory": "256Gi",
            "alibabacloud.com/gpu-count": "8"}},
    }))
    for i in range(2):
        (cluster / "pod" / f"p{i}.yaml").write_text(yaml.dump({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"gpu-pod-{i}", "annotations": {
                "alibabacloud.com/gpu-count": "1",
                "alibabacloud.com/gpu-milli": "500",
                "alibabacloud.com/gpu-card-model": "V100M16"}},
            "spec": {"containers": [
                {"resources": {"requests": {"cpu": "4"}}}]},
        }))
    dec_path = str(tmp_path / "run_decisions.jsonl")
    out = io.StringIO()
    Applier(ApplyOptions(
        simon_config=str(tmp_path / "cc.yaml"), decisions_out=dec_path,
    )).run(out=out)
    assert f"[obs] wrote {dec_path}" in out.getvalue()
    header, rows = read_decisions(dec_path)
    assert len(rows) == 2 and rows[0]["node"] == 0
    assert rows[0]["name"] == "gpu-pod-0"

    from tpusim.cli import main as cli_main

    assert cli_main(["explain", dec_path, "--event", "0"]) == 0


def test_engine_guards():
    """Unsupported combinations fail loudly at construction: pallas has
    no provenance surface; extenders splice scores the recorder cannot
    see; the batched sweep has no per-seed surface."""
    nodes, pods = _driver_inputs()
    with pytest.raises(ValueError, match="pallas"):
        Simulator(nodes, SimulatorConfig(
            policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
            engine="pallas", record_decisions=True,
        ))
    from tpusim.sim.extender import ExtenderConfig

    with pytest.raises(ValueError, match="extenders"):
        Simulator(nodes, SimulatorConfig(
            policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
            record_decisions=True,
            extenders=(ExtenderConfig(url_prefix="http://x"),),
        ))
    from tpusim.sim.driver import dispatch_pods_batch

    sim = Simulator(nodes, SimulatorConfig(
        policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
        record_decisions=True,
    ))
    sim.set_workload_pods(pods)
    with pytest.raises(ValueError, match="record decisions"):
        dispatch_pods_batch([sim], [pods])
