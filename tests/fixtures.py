"""Golden fixtures mirroring the reference's correctness oracle.

The two typical-pod distributions below are the data fixtures of
pkg/utils/frag_test.go:13-87 (37-spec and 31-spec target workloads); the
expected values in test_frag.py are the asserted golden numbers from that
file. GPU type strings are encoded as model bitmasks.
"""

from tpusim.constants import gpu_spec_to_mask
from tpusim.types import make_typical_pods

# (cpu_milli, gpu_milli, gpu_num, gpu_spec, percentage)
_TYPICAL_GPU = [
    (6000, 465, 1, "", 9.33),
    (8000, 440, 1, "2080", 9.15),
    (8000, 475, 1, "T4", 8.76),
    (8000, 440, 1, "P100", 8.72),
    (2000, 465, 1, "", 8.68),
    (12000, 900, 1, "", 8.65),
    (4000, 900, 1, "", 8.43),
    (16000, 678, 1, "T4", 8.36),
    (8000, 500, 1, "", 8.29),
    (6000, 511, 1, "", 8.11),
    (14000, 1000, 2, "2080", 0.54),
    (4000, 1000, 1, "2080", 0.43),
    (32000, 1000, 2, "T4", 0.43),
    (16000, 1000, 1, "V100M16", 0.40),
    (64000, 1000, 2, "", 0.40),
    (10000, 1000, 2, "", 0.40),
    (11400, 1000, 1, "T4", 0.36),
    (16000, 1000, 1, "T4", 0.36),
    (4000, 1000, 2, "", 0.36),
    (14000, 1000, 2, "V100M16", 0.36),
    (8000, 1000, 4, "", 0.36),
    (16000, 1000, 2, "", 0.32),
    (2000, 1000, 1, "T4", 0.32),
    (6000, 1000, 1, "", 0.32),
    (4000, 1000, 1, "", 0.32),
    (5000, 1000, 1, "", 0.32),
    (32000, 1000, 4, "V100M16", 0.32),
    (32000, 1000, 2, "", 0.32),
    (24000, 1000, 8, "2080", 0.32),
    (40000, 1000, 4, "", 0.29),
    (32000, 1000, 8, "", 0.29),
    (32000, 1000, 1, "T4", 0.29),
    (16000, 1000, 1, "", 0.25),
    (7000, 1000, 1, "V100M16", 0.25),
    (24000, 1000, 1, "T4", 0.25),
]

_TYPICAL_WITH_NONGPU = [
    (15700, 1000, 1, "", 28.69),
    (11900, 1000, 1, "", 18.93),
    (11400, 1000, 1, "", 12.27),
    (1000, 0, 0, "", 7.36),
    (18710, 1000, 1, "", 4.85),
    (8200, 1000, 1, "", 3.79),
    (16400, 1000, 1, "", 3.31),
    (9810, 1000, 1, "", 1.97),
    (15200, 1000, 1, "", 1.87),
    (11200, 1000, 1, "", 1.81),
    (14200, 1000, 1, "", 1.76),
    (12000, 0, 0, "", 1.65),
    (14900, 1000, 1, "", 1.39),
    (60200, 1000, 4, "", 1.23),
    (64200, 1000, 8, "", 1.07),
    (32200, 1000, 4, "", 1.01),
    (17400, 1000, 2, "", 0.91),
    (30200, 1000, 2, "", 0.69),
    (16000, 1000, 1, "", 0.64),
    (15000, 1000, 1, "", 0.59),
    (64000, 1000, 8, "", 0.53),
    (15000, 0, 0, "", 0.53),
    (11910, 1000, 1, "", 0.53),
    (120200, 1000, 8, "", 0.48),
    (11300, 1000, 1, "", 0.37),
    (30000, 1000, 2, "", 0.32),
    (9800, 1000, 1, "", 0.32),
    (8000, 1000, 1, "", 0.32),
    (2000, 1000, 1, "", 0.27),
    (2000, 80, 1, "", 0.27),
    (1000, 1000, 1, "", 0.27),
]


def _rows(table):
    return [
        (cpu, milli, num, gpu_spec_to_mask(spec), pct / 100.0)
        for cpu, milli, num, spec, pct in table
    ]


def typical_pods_gpu():
    """frag_test.go:13-51 TestingGenerateGetTypicalPods (35 specs)."""
    return make_typical_pods(_rows(_TYPICAL_GPU))


def typical_pods_with_nongpu():
    """frag_test.go:53-87 TestingGenerateGetTypicalPodsWithNonGpu (31 specs)."""
    return make_typical_pods(_rows(_TYPICAL_WITH_NONGPU))


def typical_rows_gpu_host():
    """Same distribution as host-side tuples for the Bellman reference."""
    return _rows(_TYPICAL_GPU)


def random_cluster(rng, num_nodes=16):
    """Heterogeneous random cluster + the gpu typical-pod distribution, for
    engine-equivalence tests."""
    from tpusim.types import make_node_state

    gpu_cnt = rng.choice([0, 2, 4, 8], num_nodes, p=[0.15, 0.25, 0.35, 0.25])
    state = make_node_state(
        cpu_cap=rng.choice([32000, 64000, 96000, 128000], num_nodes),
        mem_cap=rng.choice([131072, 262144, 393216], num_nodes),
        gpu_cnt=gpu_cnt,
        gpu_type=[int(rng.integers(0, 4)) if g else -1 for g in gpu_cnt],
        cpu_type=rng.integers(0, 3, num_nodes),
    )
    return state, typical_pods_gpu()


def random_pods(rng, num_pods=40):
    """Random pod batch spanning cpu-only / share-GPU / multi-GPU kinds."""
    import jax.numpy as jnp
    import numpy as np

    from tpusim.types import PodSpec

    kind = rng.integers(0, 3, num_pods)  # 0 cpu-only, 1 share, 2 whole
    cpu = rng.choice([1000, 2000, 4000, 8000, 16000], num_pods).astype(np.int32)
    mem = rng.choice([1024, 4096, 16384], num_pods).astype(np.int32)
    gpu_milli = np.where(
        kind == 1, rng.choice([100, 250, 500, 750], num_pods), 1000
    ).astype(np.int32)
    gpu_milli = np.where(kind == 0, 0, gpu_milli)
    gpu_num = np.where(
        kind == 2, rng.choice([1, 2, 4], num_pods), np.where(kind == 1, 1, 0)
    ).astype(np.int32)
    # ~1/4 of GPU pods carry a model constraint over 2 random models
    mask = np.where(
        (kind > 0) & (rng.random(num_pods) < 0.25),
        (1 << rng.integers(0, 4, num_pods)) | (1 << rng.integers(0, 4, num_pods)),
        0,
    ).astype(np.int32)
    return PodSpec(
        cpu=jnp.asarray(cpu),
        mem=jnp.asarray(mem),
        gpu_milli=jnp.asarray(gpu_milli),
        gpu_num=jnp.asarray(gpu_num),
        gpu_mask=jnp.asarray(mask),
        pinned=jnp.full(num_pods, -1, jnp.int32),
    )


# Golden node-frag-score cases (frag_test.go:100-163): shared between the
# CPU suite (tests/test_frag.py) and the on-TPU lane (tests/test_tpu.py)
# so the two cannot silently diverge.
# (cpu_left, gpus, gpu_model, distribution, expected_score)
FRAG_SCORE_GOLDENS = [
    (1000, [200, 1000, 1000, 500], "1080", "gpu", 2566.62),
    (1000, [1000, 1000, 1000, 1000], "1080", "gpu", 3802.40),
    (1000, [1000] * 8, "1080", "gpu", 7604.80),
    (64000, [1000] * 8, "P100", "nongpu", 887.20),
    (32000, [1000] * 4 + [0] * 4, "P100", "nongpu", 554.4),
    (0, [1000] * 4 + [0] * 4, "P100", "nongpu", 4000.0),
]


def frag_golden_score(case):
    """Evaluate one FRAG_SCORE_GOLDENS case → (actual, expected)."""
    import jax.numpy as jnp
    import numpy as np

    from tpusim.constants import GPU_MODEL_IDS
    from tpusim.ops import frag

    cpu_left, gpus, model, dist, expected = case
    tp = typical_pods_gpu() if dist == "gpu" else typical_pods_with_nongpu()
    g = np.zeros(8, np.int32)
    g[: len(gpus)] = gpus
    actual = float(
        frag.node_frag_score(
            jnp.int32(cpu_left), jnp.asarray(g),
            jnp.int32(GPU_MODEL_IDS[model]), tp,
        )
    )
    return actual, expected
