"""Test harness: force an 8-device virtual CPU mesh so unit tests run
anywhere without touching TPU hardware (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).

This environment pre-registers an 'axon' TPU-tunnel PJRT plugin via
sitecustomize *before* conftest runs, and plain JAX_PLATFORMS env tweaks do
not stop its (potentially hanging) backend init. So: update the live jax
config and drop the factory registration directly — both happen before the
first backend initialization, which is what matters.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
