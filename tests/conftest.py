"""Test harness: force an 8-device virtual CPU mesh so unit tests run
anywhere without touching TPU hardware (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).

This environment pre-registers an 'axon' TPU-tunnel PJRT plugin via
sitecustomize *before* conftest runs, and plain JAX_PLATFORMS env tweaks do
not stop its (potentially hanging) backend init. So: update the live jax
config and drop the factory registration directly — both happen before the
first backend initialization, which is what matters.

TPU lane: `TPUSIM_TPU_TESTS=1 pytest -m tpu` keeps the accelerator backend
registered and runs only the `tpu`-marked on-device tests
(tests/test_tpu.py) — golden frag values and engine equivalence asserted
on real TPU numerics. Without the env var, tpu-marked tests auto-skip and
everything else runs on the virtual CPU mesh as before.
"""

import os

import pytest

TPU_LANE = os.environ.get("TPUSIM_TPU_TESTS") == "1"

if not TPU_LANE:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if not TPU_LANE:
    jax.config.update("jax_platforms", "cpu")

    from jax._src import xla_bridge as _xb  # noqa: E402

    _xb._backend_factories.pop("axon", None)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "tpu: on-accelerator tests (TPUSIM_TPU_TESTS=1 pytest -m tpu)"
    )
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 lane (pytest -m 'not slow'); run "
        "explicitly via `make resume-smoke` or plain pytest",
    )


def pytest_collection_modifyitems(config, items):
    if TPU_LANE:
        return
    skip = pytest.mark.skip(reason="TPU lane disabled (set TPUSIM_TPU_TESTS=1)")
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip)
