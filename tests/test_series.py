"""tpusim.obs series + server — the live-telemetry plane (ISSUE 5).

The contracts under test:
  (1) the in-scan SeriesSample stream is bit-identical across the flat,
      blocked, sequential, and shard_map engines (every field is an
      integer reduction over state the engines maintain identically);
  (2) the series is continuous across checkpoint kill/resume (the
      stride clock rides the carry's counter) and across fault-path
      segmentation (pos rebased onto the run clock, retry depth
      stamped per segment), and bit-reproducible under a fixed seed;
  (3) a /metrics scrape of a published record is byte-equal to the
      write_prometheus textfile and parses as strict exposition text;
  (4) `tpusim serve` observes a run from its artifact directory alone;
  (5) Prometheus label values escape/unescape hostile characters
      (backslash, quote, newline) round-trip exactly;
  (6) the JSONL series block round-trips and `tpusim report` renders it
      without recomputation.

Compile-heavy cases (extra engine builds) are slow-marked into the
`make resume-smoke` lane to hold the tier-1 time budget; the tier-1
subset pins the table-engine driver path plus the host-side surfaces.
"""

import json
import os
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import random_cluster, random_pods
from tpusim.io.trace import NodeRow, PodRow
from tpusim.obs import emitters, series
from tpusim.policies import make_policy
from tpusim.sim.driver import Simulator, SimulatorConfig
from tpusim.sim.engine import EV_CREATE, EV_DELETE, make_replay
from tpusim.sim.table_engine import build_pod_types, make_table_replay

EVERY = 4


def _mixed_events(num_pods, rng):
    kinds, idxs, seen = [], [], set()
    for i in range(num_pods):
        kinds.append(EV_CREATE)
        idxs.append(i)
        if rng.random() < 0.3 and i > 0:
            victim = int(rng.integers(0, i + 1))
            if victim not in seen:
                seen.add(victim)
                kinds.append(EV_DELETE)
                idxs.append(victim)
    return jnp.asarray(kinds, jnp.int32), jnp.asarray(idxs, jnp.int32)


@pytest.mark.slow
def test_series_engine_invariant():
    """The same create/delete mix yields a bit-identical SeriesSample
    stream — sentinels included — on the flat, blocked, sequential, and
    shard_map engines, at a multi-policy config that exercises the
    minmax normalization path of score_stats.

    slow-marked (tier-1 budget): four engine compiles; the tier-1 lane
    still pins the table-engine series through the driver tests below."""
    from tpusim.parallel import make_mesh, pad_nodes, shard_state
    from tpusim.parallel.shard_engine import make_shardmap_table_replay

    rng = np.random.default_rng(7)
    state, tp = random_cluster(rng, num_nodes=24)
    pods = random_pods(rng, num_pods=40)
    ev_kind, ev_pod = _mixed_events(40, rng)
    policies = [
        (make_policy("FGDScore"), 1000),
        (make_policy("BestFitScore"), 500),
    ]
    key = jax.random.PRNGKey(3)
    rank = jnp.asarray(rng.permutation(24).astype(np.int32))
    types = build_pod_types(pods)

    flat = make_table_replay(
        policies, gpu_sel="FGDScore", block_size=-1, series_every=EVERY
    )(state, pods, types, ev_kind, ev_pod, tp, key, rank)
    blocked = make_table_replay(
        policies, gpu_sel="FGDScore", block_size=8, series_every=EVERY
    )(state, pods, types, ev_kind, ev_pod, tp, key, rank)
    seq = make_replay(
        policies, gpu_sel="FGDScore", report=False, series_every=EVERY
    )(state, pods, ev_kind, ev_pod, tp, key, rank)
    mesh = make_mesh(4)
    st_p, rank_p = pad_nodes(state, rank, 4)
    shard = make_shardmap_table_replay(
        policies, mesh, gpu_sel="FGDScore", series_every=EVERY
    )(shard_state(st_p, mesh), pods, types, ev_kind, ev_pod, tp, key,
      rank_p)

    assert flat.series is not None
    for name, out in (("blocked", blocked), ("seq", seq),
                      ("shard", shard)):
        for f in series.SeriesSample._fields:
            assert np.array_equal(
                np.asarray(getattr(flat.series, f)),
                np.asarray(getattr(out.series, f)),
            ), (name, f)
        assert np.array_equal(
            np.asarray(out.placed_node), np.asarray(flat.placed_node)
        ), name
    # the mix actually produced real samples on the stride grid
    pos = np.asarray(flat.series.pos)
    real = pos[pos >= 0]
    assert len(real) > 2 and np.array_equal(real % EVERY, np.zeros_like(real))
    # trajectory untouched by sampling: same placements as a series-free
    # build of the same engine
    bare = make_table_replay(policies, gpu_sel="FGDScore", block_size=-1)(
        state, pods, types, ev_kind, ev_pod, tp, key, rank
    )
    assert np.array_equal(
        np.asarray(bare.placed_node), np.asarray(flat.placed_node)
    )


# ---------------------------------------------------------------------------
# driver surface (table engine — the tier-1 subset)
# ---------------------------------------------------------------------------


def _driver_inputs():
    rng = np.random.default_rng(31)
    nodes = [
        NodeRow(f"n{i}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], 12))
    ]
    pods = [
        PodRow(f"p{i}", int(rng.choice([1000, 4000])), 1024,
               int(rng.choice([0, 1])), 500)
        for i in range(30)
    ]
    return nodes, pods


def _make_sim(nodes, pods, every=0, ckdir=""):
    sim = Simulator(nodes, SimulatorConfig(
        policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
        report_per_event=False, series_every=EVERY, seed=42,
        checkpoint_every=every, checkpoint_dir=ckdir,
    ))
    sim.set_workload_pods(pods)
    return sim


def test_driver_series_to_report(tmp_path, capsys):
    """run() surfaces a filtered SeriesLog on the run-global event grid;
    the JSONL record block round-trips bit-exactly and `tpusim report`
    renders it straight from the file."""
    from tpusim.cli import main as cli_main

    nodes, pods = _driver_inputs()
    sim = _make_sim(nodes, pods)
    res = sim.run()
    log = res.series
    assert log is not None
    pos = np.asarray(log.pos)
    assert len(pos) > 2
    assert np.array_equal(pos % EVERY, np.zeros_like(pos))
    assert np.array_equal(pos, np.sort(pos))
    assert np.asarray(log.util_hist).shape == (len(pos), series.UTIL_BUCKETS)
    assert np.asarray(log.frag).shape == (len(pos), 7)
    # no faults in this run: DOWN and retry columns are all zero
    assert not np.asarray(log.nodes_down).any()
    assert not np.asarray(log.retry_depth).any()

    block = series.series_to_record(
        log, EVERY, [n for n, _ in sim.cfg.policies]
    )
    back = series.series_from_record(block)
    for f in series.SeriesLog._fields:
        assert np.array_equal(
            np.asarray(getattr(log, f)), np.asarray(getattr(back, f))
        ), f
    with pytest.raises(ValueError):
        series.series_from_record({"schema": "bogus"})

    # record → JSONL → tpusim report, no recomputation
    record = emitters.build_record(sim.run_telemetry(), series=block)
    path = str(tmp_path / "run.jsonl")
    emitters.append_jsonl(path, record)
    assert cli_main(["report", path]) == 0
    out = capsys.readouterr().out
    assert f"stride {EVERY} events" in out
    assert "feasible_nodes" in out and "frag_q3_satisfied" in out
    # a record without a series block exits 2 with a one-line error
    bare = str(tmp_path / "bare.jsonl")
    emitters.append_jsonl(bare, emitters.build_record(sim.run_telemetry()))
    assert cli_main(["report", bare]) == 2

    # Chrome counter tracks share the vocabulary
    tracks = series.series_tracks(log)
    assert set(tracks) >= {
        "series_feasible_nodes", "series_nodes_down", "series_retry_depth",
    } | {f"series_frag_{n}" for n in series.FRAG_CATEGORY_NAMES}


def test_series_config_validation():
    nodes, pods = _driver_inputs()
    with pytest.raises(ValueError, match="series_every must be >= 0"):
        Simulator(nodes, SimulatorConfig(series_every=-1))
    with pytest.raises(ValueError, match="pallas"):
        Simulator(nodes, SimulatorConfig(series_every=2, engine="pallas"))


@pytest.mark.slow
def test_series_survive_kill_resume(tmp_path):
    """Series continuity across checkpoint kill/resume: the stride clock
    is the carry's event counter, so the resumed run's SeriesLog is
    bit-identical to the uninterrupted run's. slow-marked: the chunked
    replay re-traces the scan per segment length."""
    import tpusim.io.storage as storage

    nodes, pods = _driver_inputs()
    r0 = _make_sim(nodes, pods).run()

    real_save = storage.save_checkpoint

    def killing_save(*a, **k):
        real_save(*a, **k)
        raise KeyboardInterrupt("simulated preemption")

    storage.save_checkpoint = killing_save
    try:
        with pytest.raises(KeyboardInterrupt):
            _make_sim(nodes, pods, every=10, ckdir=str(tmp_path)).run()
    finally:
        storage.save_checkpoint = real_save
    assert os.listdir(tmp_path)

    sim2 = _make_sim(nodes, pods, every=10, ckdir=str(tmp_path))
    r2 = sim2.run()
    assert any("[Checkpoint] resumed replay" in l for l in sim2.log.lines)
    for f in series.SeriesLog._fields:
        assert np.array_equal(
            np.asarray(getattr(r0.series, f)),
            np.asarray(getattr(r2.series, f)),
        ), f


@pytest.mark.slow
def test_series_fault_segments():
    """Fault runs: every segment opens with a sample of the post-fault
    cluster rebased onto the run-global clock, the host stamps the
    retry-queue depth, DOWN nodes show up in nodes_down — and the whole
    log is bit-reproducible under a fixed seed. slow-marked: the fault
    loop re-traces the scan per distinct segment length."""
    from tpusim.sim.faults import FaultConfig

    nodes, pods = _driver_inputs()
    fcfg = dict(mtbf_events=5, mttr_events=7, evict_every_events=11, seed=9)
    res = _make_sim(nodes, pods).run_with_faults(FaultConfig(**fcfg))
    log = res.series
    assert log is not None
    pos = np.asarray(log.pos)
    assert len(pos) > 2 and np.array_equal(pos, np.sort(pos))
    # faults actually happened and the series saw them
    assert np.asarray(log.nodes_down).max() > 0
    assert np.asarray(log.retry_depth).max() > 0
    res2 = _make_sim(nodes, pods).run_with_faults(FaultConfig(**fcfg))
    for f in series.SeriesLog._fields:
        assert np.array_equal(
            np.asarray(getattr(log, f)),
            np.asarray(getattr(res2.series, f)),
        ), f


@pytest.mark.slow
def test_series_openb_acceptance(tmp_path):
    """The ISSUE 5 acceptance criterion on real trace data: a
    fault-injected openb-prefix run with series sampling yields (a) a
    bit-identical series across the table, blocked, sequential, and
    shard_map engines and across a checkpoint kill/resume, and (b/c) a
    /metrics scrape over real HTTP that parses as exposition text and is
    byte-equal to the write_prometheus textfile of the same record."""
    from tpusim.io.trace import load_node_csv, load_pod_csv
    from tpusim.obs.server import MonitorServer
    from tpusim.sim.faults import FaultConfig

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    node_csv = os.path.join(repo, "data/csv/openb_node_list_gpu_node.csv")
    pod_csv = os.path.join(repo, "data/csv/openb_pod_list_default.csv")
    if not (os.path.isfile(node_csv) and os.path.isfile(pod_csv)):
        pytest.skip("openb traces not present")
    nodes = load_node_csv(node_csv)[:150]
    pods = load_pod_csv(pod_csv)[:80]
    fcfg = dict(mtbf_events=25, mttr_events=30, seed=9)

    def run(**cfg_kw):
        sim = Simulator(nodes, SimulatorConfig(
            policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
            report_per_event=False, series_every=8, seed=42, **cfg_kw,
        ))
        sim.set_workload_pods(pods)
        return sim, sim.run_with_faults(FaultConfig(**fcfg))

    sim_t, table = run()
    _, blocked = run(block_size=16)
    _, seq = run(engine="sequential")
    _, shard = run(mesh=4)
    for name, res in (("blocked", blocked), ("sequential", seq),
                      ("shard", shard)):
        for f in series.SeriesLog._fields:
            assert np.array_equal(
                np.asarray(getattr(table.series, f)),
                np.asarray(getattr(res.series, f)),
            ), (name, f)
    assert len(np.asarray(table.series.pos)) > 2

    # kill/resume continuity on the same prefix (unfaulted run: the
    # chunked dispatch owns the checkpoint layout)
    import tpusim.io.storage as storage

    sim_p = Simulator(nodes, SimulatorConfig(
        policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
        report_per_event=False, series_every=8, seed=42,
    ))
    sim_p.set_workload_pods(pods)
    r0 = sim_p.run()
    ckdir = str(tmp_path / "ck")
    real_save = storage.save_checkpoint

    def killing_save(*a, **k):
        real_save(*a, **k)
        raise KeyboardInterrupt("simulated preemption")

    storage.save_checkpoint = killing_save
    try:
        sim_k = Simulator(nodes, SimulatorConfig(
            policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
            report_per_event=False, series_every=8, seed=42,
            checkpoint_every=30, checkpoint_dir=ckdir,
        ))
        sim_k.set_workload_pods(pods)
        with pytest.raises(KeyboardInterrupt):
            sim_k.run()
    finally:
        storage.save_checkpoint = real_save
    sim_r = Simulator(nodes, SimulatorConfig(
        policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
        report_per_event=False, series_every=8, seed=42,
        checkpoint_every=30, checkpoint_dir=ckdir,
    ))
    sim_r.set_workload_pods(pods)
    r2 = sim_r.run()
    assert any("[Checkpoint] resumed replay" in l for l in sim_r.log.lines)
    for f in series.SeriesLog._fields:
        assert np.array_equal(
            np.asarray(getattr(r0.series, f)),
            np.asarray(getattr(r2.series, f)),
        ), f

    # live endpoint: publish the fault run's record, scrape, compare
    block = series.series_to_record(
        table.series, 8, [n for n, _ in sim_t.cfg.policies]
    )
    record = emitters.build_record(sim_t.run_telemetry(), series=block)
    path = str(tmp_path / "m.prom")
    emitters.write_prometheus(path, record)
    srv = MonitorServer(":0").start()
    try:
        srv.publish_record(record)
        scrape = urllib.request.urlopen(
            srv.url + "/metrics", timeout=10).read().decode()
    finally:
        srv.stop()
    assert scrape == open(path).read()
    assert emitters.parse_prometheus_text(scrape)
    # the record renders without recomputation
    assert "feasible_nodes" in series.format_report(block)


# ---------------------------------------------------------------------------
# host-side surfaces: escaping, record parsing, server (no engine compiles)
# ---------------------------------------------------------------------------

HOSTILE = 'sp\\an "quoted"\nnew\\nline'


def test_prometheus_escape_roundtrip():
    assert emitters.escape_label_value(HOSTILE) == (
        r'sp\\an \"quoted\"\nnew\\nline'
    )
    assert emitters.unescape_label_value(
        emitters.escape_label_value(HOSTILE)
    ) == HOSTILE
    # the subtle case chained replaces get wrong: literal backslash-n
    assert emitters.escape_label_value("a\\nb") == r"a\\nb"
    assert emitters.unescape_label_value(r"a\\nb") == "a\\nb"
    assert emitters.unescape_label_value(r"a\nb") == "a\nb"


def _hostile_record():
    """A telemetry record whose span name carries every escaped char."""
    from tpusim.obs import Recorder

    rec = Recorder(enabled=True)
    with rec.span(HOSTILE, engine="table") as h:
        h.dispatched()
    rec.note_scan("table", counters=np.array([3, 3, 0, 0, 0, 0]),
                  pad_skips=0, events=3)
    return rec.snapshot(meta={"seed": 1}).to_record()


def test_prometheus_hostile_label_roundtrip(tmp_path):
    """A span named with backslash/quote/newline survives the textfile →
    strict parse round trip with its exact name (ISSUE 5 satellite)."""
    record = _hostile_record()
    path = str(tmp_path / "m.prom")
    emitters.write_prometheus(path, record)
    text = open(path).read()
    # single-line samples only: the newline in the name must be escaped
    parsed = emitters.parse_prometheus_text(text)
    names = {
        dict(labels).get("name")
        for (metric, labels) in parsed
        if metric.endswith("span_count")
    }
    assert HOSTILE in names
    # parser rejects torn/duplicate exposition text
    with pytest.raises(ValueError, match="duplicate"):
        emitters.parse_prometheus_text("a 1\na 1\n")
    with pytest.raises(ValueError, match="not a valid sample"):
        emitters.parse_prometheus_text('a{b="unterminated 1\n')


def test_monitor_scrape_equals_textfile(tmp_path):
    """MonitorServer /metrics is byte-equal to write_prometheus of the
    same record; /healthz and /progress serve JSON; unknown paths 404;
    an unpublished server answers 503 on /metrics."""
    from tpusim.obs.server import MonitorServer, parse_listen

    assert parse_listen(":0") == ("127.0.0.1", 0)
    assert parse_listen("8080") == ("127.0.0.1", 8080)
    assert parse_listen("0.0.0.0:9") == ("0.0.0.0", 9)
    with pytest.raises(ValueError, match="port"):
        parse_listen("host:nope")

    record = _hostile_record()
    # a series block rides along, hostile policy name included
    log = series.SeriesLog(
        pos=np.array([0, 4], np.int64),
        util_hist=np.zeros((2, series.UTIL_BUCKETS), np.int64),
        nodes_down=np.array([0, 1], np.int64),
        feasible=np.array([5, 4], np.int64),
        frag=np.zeros((2, 7), np.int64),
        score_hi=np.array([[7], [9]], np.int64),
        score_lo=np.array([[1], [2]], np.int64),
        retry_depth=np.array([0, 2], np.int64),
    )
    record["series"] = series.series_to_record(log, 4, [HOSTILE])

    srv = MonitorServer(":0").start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(srv.url + "/metrics", timeout=10)
        assert err.value.code == 503
        srv.publish_record(record)
        srv.publish_progress(phase="scan", events_done=4, events_total=8)
        scrape = urllib.request.urlopen(
            srv.url + "/metrics", timeout=10).read().decode()
        path = str(tmp_path / "m.prom")
        emitters.write_prometheus(path, record)
        assert scrape == open(path).read()
        parsed = emitters.parse_prometheus_text(scrape)
        assert parsed[("tpusim_series_retry_depth", ())] == 2.0
        assert parsed[("tpusim_series_score_hi",
                       (("policy", HOSTILE),))] == 9.0
        health = json.loads(urllib.request.urlopen(
            srv.url + "/healthz", timeout=10).read().decode())
        assert health["ok"] and health["records"] == 1
        prog = json.loads(urllib.request.urlopen(
            srv.url + "/progress", timeout=10).read().decode())
        assert prog["phase"] == "scan" and prog["events_done"] == 4
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(srv.url + "/nope", timeout=10)
        assert err.value.code == 404
    finally:
        srv.stop()


def test_serve_dir_watches_artifacts(tmp_path):
    """`tpusim serve` republishes the newest run record and reads run
    progress out of checkpoint filenames — a killed or running
    checkpointed run is observable from its artifact directory alone."""
    from tpusim.io.storage import CHECKPOINT_SUFFIX
    from tpusim.obs.server import serve_dir, watch_dir

    record = _hostile_record()
    emitters.append_jsonl(str(tmp_path / "run.jsonl"), record)
    open(str(tmp_path / f"ab12.e{25:010d}{CHECKPOINT_SUFFIX}"), "wb").close()
    open(str(tmp_path / f"ab12.e{10:010d}{CHECKPOINT_SUFFIX}"), "wb").close()

    rec, prog = watch_dir(str(tmp_path))
    assert rec is not None and rec["schema"] == record["schema"]
    assert prog["phase"] == "checkpointed" and prog["events_done"] == 25

    srv = serve_dir(str(tmp_path), listen=":0", once=True)
    try:
        scrape = urllib.request.urlopen(
            srv.url + "/metrics", timeout=10).read().decode()
        assert emitters.parse_prometheus_text(scrape)
        prog = json.loads(urllib.request.urlopen(
            srv.url + "/progress", timeout=10).read().decode())
        assert prog["events_done"] == 25
        assert prog["record_file"] == "run.jsonl"
    finally:
        srv.stop()
    # missing dir: healthy server, honest phase
    _, prog = watch_dir(str(tmp_path / "gone"))
    assert prog["phase"] == "missing-dir"


def test_serve_once_cli(tmp_path, capsys):
    """`tpusim serve DIR --once` exits 0 and prints the scrape verdict
    (the `make serve-smoke` entry)."""
    from tpusim.cli import main as cli_main

    emitters.append_jsonl(str(tmp_path / "run.jsonl"), _hostile_record())
    assert cli_main(
        ["serve", str(tmp_path), "--once", "--listen", ":0"]
    ) == 0
    err = capsys.readouterr().err
    assert "/metrics parses" in err
    # an empty dir is still healthy — nothing to scrape is not an error
    empty = tmp_path / "empty"
    empty.mkdir()
    assert cli_main(
        ["serve", str(empty), "--once", "--listen", ":0"]
    ) == 0
    assert "no run record yet" in capsys.readouterr().err


def test_heartbeat_run_level_progress():
    """The heartbeat listener hook feeds run-level numbers: `base` lifts
    segment-local counts onto the run clock, note_resume() keeps the
    rate honest, complete() fires a final tick that disarms."""
    from tpusim.obs import heartbeat

    seen = []
    heartbeat.add_listener(seen.append)
    try:
        heartbeat.configure(100, "test", sink=lambda line: None, base=40)
        heartbeat.note_resume(10)
        heartbeat.tick(20)  # segment-local 20 → run-level 60
        assert seen and seen[-1]["done"] == 60
        assert seen[-1]["total"] == 100 and not seen[-1]["final"]
        assert seen[-1]["eta"] >= 0.0
        heartbeat.complete()
        assert seen[-1]["final"] and seen[-1]["done"] == seen[-1]["total"]
        n = len(seen)
        heartbeat.complete()  # disarmed: no further notifications
        assert len(seen) == n
        # a fault SEGMENT's final tick stays on the run clock: armed
        # run-level (base + padded segment), completed with the
        # segment-local true count — never a backwards jump to
        # segment-local numbers
        heartbeat.configure(100, "test", sink=lambda line: None, base=40)
        heartbeat.complete(true_total=30)
        assert seen[-1]["done"] == 70 and seen[-1]["total"] == 70
    finally:
        heartbeat.remove_listener(seen.append)
    # a broken listener never kills the replay
    def boom(info):
        raise RuntimeError("broken listener")

    heartbeat.add_listener(boom)
    try:
        heartbeat.configure(10, "test", sink=lambda line: None)
        heartbeat.tick(5)
    finally:
        heartbeat.remove_listener(boom)


def test_sparkline_and_stats():
    assert series.sparkline([]) == ""
    assert series.sparkline([1, 1, 1]) == "▁▁▁"
    line = series.sparkline(list(range(100)), width=10)
    assert 0 < len(line) <= 11 and line[-1] == "█"
    # concat + rebase: the fault path's segment merge
    a = series.log_from_stacked(series.SeriesSample(
        pos=np.array([-1, 0, -1, 4]),
        util_hist=np.zeros((4, series.UTIL_BUCKETS), np.int32),
        nodes_down=np.zeros(4, np.int32),
        feasible=np.arange(4, dtype=np.int32),
        frag=np.zeros((4, 7), np.int32),
        score_hi=np.zeros((4, 2), np.int32),
        score_lo=np.zeros((4, 2), np.int32),
    ), base_pos=100, retry_depth=3)
    assert np.array_equal(np.asarray(a.pos), [100, 104])
    assert np.array_equal(np.asarray(a.feasible), [1, 3])
    assert np.array_equal(np.asarray(a.retry_depth), [3, 3])
    assert series.concat_series([]) is None
    both = series.concat_series([a, None, a])
    assert np.array_equal(np.asarray(both.pos), [100, 104, 100, 104])
