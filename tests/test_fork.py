"""Warm-state forking of chunked replays (ISSUE 16 tentpole).

The contract under test: `schedule_pods_fork` replays the spliced stream
`base[:fork_event] + tail` resumed from the base run's persisted
mid-trace checkpoint ladder, bit-identical to the same stream replayed
from event 0 — table and shard engines alike. Around it: the
nearest-at-or-before walk-back rule, the loud degrade on a missing
source, the weight-change digest rejection (the carry embeds the weight
vector), the `checkpoint_keep` retention knob, and the EV_SKIP trailing
-pad inertness the serving wave's lane geometry leans on.
`make resume-smoke` runs this file as part of the fast CI gate.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import random_cluster, random_pods
from tpusim.io.trace import NodeRow, PodRow, build_events
from tpusim.policies import make_policy
from tpusim.sim.driver import Simulator, SimulatorConfig
from tpusim.sim.engine import EV_CREATE, EV_DELETE, EV_SKIP
from tpusim.sim.table_engine import build_pod_types, make_table_replay


def _driver_inputs():
    rng = np.random.default_rng(11)
    nodes = [
        NodeRow(f"n{i}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], 10))
    ]
    pods = [
        PodRow(f"p{i}", int(rng.choice([1000, 4000])), 1024,
               int(rng.choice([0, 1])), 500)
        for i in range(24)
    ]
    return nodes, pods


def _sim(nodes, ckdir, every=4, keep=-1, mesh=0, weight=1000, seed=42):
    return Simulator(nodes, SimulatorConfig(
        policies=(("FGDScore", weight),), gpu_sel_method="FGDScore",
        checkpoint_every=every, checkpoint_keep=keep,
        checkpoint_dir=str(ckdir), mesh=mesh, seed=seed,
    ))


# a divergent tail over the base workload's pod vocabulary: kill two
# placed pods, re-create one of them
_TAIL_KIND = [EV_DELETE, EV_DELETE, EV_CREATE]
_TAIL_POD = [0, 3, 0]


def _assert_equal(r0, r1):
    assert np.array_equal(np.asarray(r0.placed_node),
                          np.asarray(r1.placed_node))
    assert np.array_equal(np.asarray(r0.dev_mask), np.asarray(r1.dev_mask))
    assert np.array_equal(np.asarray(r0.creation_rank),
                          np.asarray(r1.creation_rank))
    for a, b in zip(jax.tree.leaves(r0.state), jax.tree.leaves(r1.state)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def _base_and_fork(nodes, pods, warm_dir, cold_dir, fev, mesh=0,
                   every=4):
    """Run the base (persisting its ladder under warm_dir), then the
    same fork twice: warm (fresh Simulator over the ladder) and cold
    (fresh Simulator over an empty dir — the from-event-0 reference)."""
    base = _sim(nodes, warm_dir, every=every, mesh=mesh)
    base.schedule_pods(pods)
    warm = _sim(nodes, warm_dir, every=every, mesh=mesh)
    rw = warm.schedule_pods_fork(pods, fev, _TAIL_KIND, _TAIL_POD)
    cold = _sim(nodes, cold_dir, every=every, mesh=mesh)
    rc = cold.schedule_pods_fork(pods, fev, _TAIL_KIND, _TAIL_POD)
    return warm, rw, cold, rc


def test_fork_warm_equals_cold_table(tmp_path):
    """The headline: a warm fork (resumed mid-trace from the base
    ladder) is bit-identical to the from-event-0 replay of the spliced
    stream — and actually warm (source_cursor > 0, device executed only
    the divergent tail plus at most one chunk of shared prefix)."""
    nodes, pods = _driver_inputs()
    e = len(build_events(pods, False)[0])
    fev = e - 2
    warm, rw, cold, rc = _base_and_fork(
        nodes, pods, tmp_path / "a", tmp_path / "b", fev
    )
    _assert_equal(rw, rc)

    assert warm.last_fork["degrade"] is False
    assert warm.last_fork["source_cursor"] > 0
    # the latency win the serving plane measures: tail + walk-back
    assert warm.last_fork["events_executed"] <= len(_TAIL_KIND) + 4
    assert warm.last_fork["events_total"] == fev + len(_TAIL_KIND)
    # the cold twin degraded LOUDLY (no source in an empty dir)
    assert cold.last_fork["degrade"] is True
    assert cold.last_fork["source_cursor"] == 0
    assert any("[Degrade]" in l and "fork source" in l
               for l in cold.log.lines)


def test_fork_boundary_and_midchunk_walkback(tmp_path):
    """The nearest-at-or-before rule: forking exactly ON a checkpoint
    rung resumes at that rung; forking mid-chunk walks BACK to the rung
    below (never forward — a newer carry has consumed post-divergence
    events), and both replays stay exact."""
    nodes, pods = _driver_inputs()
    base = _sim(nodes, tmp_path / "a", every=4)
    base.schedule_pods(pods)

    at_rung = _sim(nodes, tmp_path / "a", every=4)
    r1 = at_rung.schedule_pods_fork(pods, 8, _TAIL_KIND, _TAIL_POD)
    assert at_rung.last_fork["source_cursor"] == 8

    mid = _sim(nodes, tmp_path / "a", every=4)
    r2 = mid.schedule_pods_fork(pods, 10, _TAIL_KIND, _TAIL_POD)
    assert mid.last_fork["source_cursor"] == 8  # walked back, not up

    cold1 = _sim(nodes, tmp_path / "b", every=4)
    _assert_equal(r1, cold1.schedule_pods_fork(
        pods, 8, _TAIL_KIND, _TAIL_POD
    ))
    cold2 = _sim(nodes, tmp_path / "c", every=4)
    _assert_equal(r2, cold2.schedule_pods_fork(
        pods, 10, _TAIL_KIND, _TAIL_POD
    ))


def test_weight_change_fork_finds_no_source(tmp_path):
    """The carry embeds the weight vector (blocked summaries), so a
    weight-changing fork can NEVER match a base checkpoint: the run
    digest differs, the lookup misses, and the run degrades loudly to a
    (correct, cold) full replay under ITS weights — the driver-level
    fact behind the svc layer's 400 rejection."""
    nodes, pods = _driver_inputs()
    base = _sim(nodes, tmp_path / "a", weight=1000)
    base.schedule_pods(pods)

    other = _sim(nodes, tmp_path / "a", weight=500)
    ro = other.schedule_pods_fork(pods, 8, _TAIL_KIND, _TAIL_POD)
    assert other.last_fork["degrade"] is True
    assert other.last_fork["source_cursor"] == 0
    cold = _sim(nodes, tmp_path / "b", weight=500)
    _assert_equal(ro, cold.schedule_pods_fork(
        pods, 8, _TAIL_KIND, _TAIL_POD
    ))


def test_checkpoint_keep_retention(tmp_path):
    """SimulatorConfig.checkpoint_keep: 0 prunes the ladder on
    completion (the historical resume-only behavior), -1 keeps every
    rung (the fork-source mode), N > 0 keeps the newest N."""
    from tpusim.io.storage import iter_checkpoints
    from tpusim.sim.driver import _bucket_sizes

    nodes, pods = _driver_inputs()
    e = len(build_events(pods, False)[0])
    # the chunked path runs the BUCKET-padded stream (pow2 adaptation
    # for small runs); saves land at every, 2*every, ... < e2
    _, e2 = _bucket_sizes(len(pods), e, 512)
    rungs = (e2 - 1) // 4

    def _ladder(keep, d):
        sim = _sim(nodes, d, every=4, keep=keep)
        sim.schedule_pods(pods)
        return iter_checkpoints(str(d), sim.last_run_digest)

    assert _ladder(0, tmp_path / "k0") == []
    full = _ladder(-1, tmp_path / "kall")
    assert len(full) == rungs
    assert [c for c, _ in full] == sorted(
        (c for c, _ in full), reverse=True
    )
    assert len(_ladder(2, tmp_path / "k2")) == 2


@pytest.mark.slow
def test_fork_shard_engine(tmp_path):
    """Warm-vs-cold bit-identity on the shard engine (mesh=4): the
    gather-to-host checkpoint snapshot round-trips through the fork
    path exactly like the single-device carry — and agrees with the
    table engine's fork result."""
    nodes, pods = _driver_inputs()
    e = len(build_events(pods, False)[0])
    fev = e - 3
    warm, rw, cold, rc = _base_and_fork(
        nodes, pods, tmp_path / "a", tmp_path / "b", fev, mesh=4
    )
    _assert_equal(rw, rc)
    assert warm.last_fork["degrade"] is False
    assert warm.last_fork["source_cursor"] > 0

    tbl = _sim(nodes, tmp_path / "c")
    _assert_equal(rw, tbl.schedule_pods_fork(
        pods, fev, _TAIL_KIND, _TAIL_POD
    ))


def test_fork_ev_kinds_pin():
    """The svc fork-tail vocabulary is the engine's event vocabulary:
    a tail entry's kind field IS EV_CREATE/EV_DELETE. If the engine
    constants ever move, the wire format must be versioned, not
    silently re-pointed."""
    from tpusim.svc.jobs import FORK_EV_KINDS

    assert FORK_EV_KINDS == (EV_CREATE, EV_DELETE)


@pytest.mark.slow  # tier-1 trim, ISSUE 16: rides resume-smoke
def test_trailing_skip_pad_inertness():
    """The wave-lane geometry contract (sim.driver.ChunkWave): the scan
    body splits the PRNG key BEFORE branching on kind, so trailing
    EV_SKIP padding advances only the key and the skip counter — state,
    placements, masks, failures are byte-identical with and without the
    pad, and the counters differ ONLY in the skip slot by exactly the
    pad count."""
    from tpusim.obs.counters import COUNTER_FIELDS

    rng = np.random.default_rng(7)
    state, tp = random_cluster(rng, num_nodes=16)
    pods = random_pods(rng, num_pods=20)
    ev_kind = jnp.zeros(20, jnp.int32)
    ev_pod = jnp.arange(20, dtype=jnp.int32)
    key = jax.random.PRNGKey(3)
    rank = jnp.asarray(rng.permutation(16).astype(np.int32))
    types = build_pod_types(pods)
    fn = make_table_replay([(make_policy("FGDScore"), 1000)],
                           gpu_sel="FGDScore")

    def _run(pad):
        ek = jnp.concatenate(
            [ev_kind, jnp.full(pad, EV_SKIP, ev_kind.dtype)]
        )
        ep = jnp.concatenate([ev_pod, jnp.zeros(pad, ev_pod.dtype)])
        carry = fn.init_carry(state, pods, types, tp, key, rank)
        carry, _ = fn.run_chunk(carry, pods, types, ek, ep, tp, rank)
        st, placed, masks, failed = fn.finish(carry)
        return st, placed, masks, failed, np.asarray(carry.ctr)

    s0, p0, m0, f0, c0 = _run(0)
    s1, p1, m1, f1, c1 = _run(6)
    assert np.array_equal(np.asarray(p0), np.asarray(p1))
    assert np.array_equal(np.asarray(m0), np.asarray(m1))
    assert np.array_equal(np.asarray(f0), np.asarray(f1))
    for a, b in zip(jax.tree.leaves(s0), jax.tree.leaves(s1)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    skip_i = COUNTER_FIELDS.index("skips")
    diff = c1 - c0
    assert diff[skip_i] == 6
    assert not np.any(np.delete(diff, skip_i))
