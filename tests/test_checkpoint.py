"""Exact checkpoint/resume of the chunked event scan (ISSUE 2 tentpole).

The contract under test: for any partition of the event stream — including
a kill + fresh-process resume from a persisted checkpoint — the chunked
replay reproduces the uninterrupted run's placements, telemetry, metrics,
and final cluster state EXACTLY (table engine and shard engine alike).
`make resume-smoke` runs this file alone as the fast CI gate.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import random_cluster, random_pods
from tpusim.io.trace import NodeRow, PodRow, pods_to_specs
from tpusim.policies import make_policy
from tpusim.sim.driver import Simulator, SimulatorConfig
from tpusim.sim.engine import EV_CREATE, EV_DELETE
from tpusim.sim.table_engine import build_pod_types, make_table_replay

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _events_with_deletes(num_pods, rng):
    kinds, idxs = [], []
    seen = set()
    for i in range(num_pods):
        kinds.append(EV_CREATE)
        idxs.append(i)
        if rng.random() < 0.34 and i > 0:
            victim = int(rng.integers(0, i + 1))
            if victim not in seen:
                seen.add(victim)
                kinds.append(EV_DELETE)
                idxs.append(victim)
    return jnp.asarray(kinds, jnp.int32), jnp.asarray(idxs, jnp.int32)


def _assert_equal(r0, r1):
    assert np.array_equal(np.asarray(r0.placed_node), np.asarray(r1.placed_node))
    assert np.array_equal(np.asarray(r0.dev_mask), np.asarray(r1.dev_mask))
    assert np.array_equal(np.asarray(r0.ever_failed), np.asarray(r1.ever_failed))
    assert np.array_equal(np.asarray(r0.event_node), np.asarray(r1.event_node))
    assert np.array_equal(np.asarray(r0.event_dev), np.asarray(r1.event_dev))
    for a, b in zip(jax.tree.leaves(r0.state), jax.tree.leaves(r1.state)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize(
    "policy,gpu_sel,block",
    [
        ("FGDScore", "FGDScore", 0),  # flat carry
        # tier-1 keeps the flat config; each further variant (blocked
        # summaries + minmax extrema, blocked none-normalize, per-event
        # random key chains) compiles its own engine and runs under
        # `make resume-smoke` / plain pytest
        pytest.param("BestFitScore", "best", 8, marks=pytest.mark.slow),
        pytest.param("FGDScore", "FGDScore", 8, marks=pytest.mark.slow),
        pytest.param("RandomScore", "random", 0, marks=pytest.mark.slow),
    ],
    ids=lambda p: str(p),
)
def test_chunk_api_any_boundary(policy, gpu_sel, block):
    """init_carry -> run_chunk* -> finish equals one replay() for EVERY cut
    point of a randomized create/delete mix, with a host round-trip of the
    carry between chunks (what a checkpoint file does)."""
    rng = np.random.default_rng(7)
    state, tp = random_cluster(rng, num_nodes=24)
    pods = random_pods(rng, num_pods=40)
    ev_kind, ev_pod = _events_with_deletes(40, rng)
    policies = [(make_policy(policy), 1000)]
    key = jax.random.PRNGKey(3)
    rank = jnp.asarray(rng.permutation(24).astype(np.int32))
    types = build_pod_types(pods)
    fn = make_table_replay(policies, gpu_sel=gpu_sel, block_size=block)
    ref = fn(state, pods, types, ev_kind, ev_pod, tp, key, rank)

    e = int(ev_kind.shape[0])
    # every cut length compiles its own chunk; two cuts (the first-event
    # boundary and mid-stream) cover the edge and bulk cases without
    # blowing the tier-1 time budget
    for cut in (1, e // 2):
        carry = fn.init_carry(state, pods, types, tp, key, rank)
        parts = []
        for a, b in ((0, cut), (cut, e)):
            carry, (nodes, devs) = fn.run_chunk(
                carry, pods, types, ev_kind[a:b], ev_pod[a:b], tp, rank
            )
            # host round-trip: exactly what serialization does to the carry
            carry = jax.tree.map(lambda x: jnp.asarray(np.asarray(x)), carry)
            parts.append((np.asarray(nodes), np.asarray(devs)))
        st, placed, masks, failed = fn.finish(carry)
        assert np.array_equal(np.asarray(placed), np.asarray(ref.placed_node))
        assert np.array_equal(np.asarray(masks), np.asarray(ref.dev_mask))
        assert np.array_equal(np.asarray(failed), np.asarray(ref.ever_failed))
        assert np.array_equal(
            np.concatenate([n for n, _ in parts]), np.asarray(ref.event_node)
        )
        for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(ref.state)):
            assert np.array_equal(np.asarray(a), np.asarray(b))


def _driver_inputs():
    rng = np.random.default_rng(31)
    nodes = [
        NodeRow(f"n{i}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], 12))
    ]
    pods = [
        PodRow(f"p{i}", int(rng.choice([1000, 4000])), 1024,
               int(rng.choice([0, 1])), 500)
        for i in range(30)
    ]
    return nodes, pods


def _run_driver(nodes, pods, every, ckdir, mesh=0, seed=42):
    sim = Simulator(nodes, SimulatorConfig(
        policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
        report_per_event=True, checkpoint_every=every,
        checkpoint_dir=ckdir, mesh=mesh, seed=seed,
    ))
    sim.set_workload_pods(pods)
    sim.set_typical_pods()
    specs = pods_to_specs(pods)
    out = sim.run_events(
        sim.init_state, specs, jnp.zeros(len(pods), jnp.int32),
        jnp.arange(len(pods), dtype=jnp.int32), jax.random.PRNGKey(2),
    )
    return sim, out


@pytest.mark.slow
def test_driver_chunked_matches_plain(tmp_path):
    """checkpoint_every routes run_events through the chunked dispatch with
    results — including the reconstructed metric series — byte-identical
    to the unsegmented scan, and completed runs leave no files behind.

    resume-smoke only (ISSUE 17 tier-1 buyback): every assertion here is
    a strict subset of test_kill_and_resume_bit_identity's (same inputs,
    same chunked-vs-plain compare, same metric series, same empty-dir
    prune check) — tier-1 keeps that one as the representative pin."""
    nodes, pods = _driver_inputs()
    _, r0 = _run_driver(nodes, pods, 0, "")
    _, r1 = _run_driver(nodes, pods, 10, str(tmp_path))
    _assert_equal(r0, r1)
    for a, b in zip(r0.metrics, r1.metrics):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert os.listdir(tmp_path) == []  # pruned on completion


def test_kill_and_resume_bit_identity(tmp_path):
    """The headline resume-smoke: kill the run right after a mid-trace
    checkpoint landed, re-run with identical inputs in a fresh Simulator,
    and the resumed run must (a) actually resume (log line) and (b)
    reproduce the uninterrupted run's placements, metrics, and final
    tables exactly."""
    import tpusim.io.storage as storage

    nodes, pods = _driver_inputs()
    _, r0 = _run_driver(nodes, pods, 0, "")

    real_save = storage.save_checkpoint
    saves = []

    def killing_save(*a, **k):
        path = real_save(*a, **k)
        saves.append(path)
        raise KeyboardInterrupt("simulated preemption")

    storage.save_checkpoint = killing_save
    try:
        with pytest.raises(KeyboardInterrupt):
            _run_driver(nodes, pods, 10, str(tmp_path))
    finally:
        storage.save_checkpoint = real_save
    assert saves and os.listdir(tmp_path)  # the checkpoint survived the kill

    sim, r2 = _run_driver(nodes, pods, 10, str(tmp_path))
    assert any("[Checkpoint] resumed replay" in l for l in sim.log.lines)
    _assert_equal(r0, r2)
    for a, b in zip(r0.metrics, r2.metrics):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert os.listdir(tmp_path) == []


def test_resume_is_content_addressed(tmp_path):
    """A checkpoint from run A must never be resumed by run B: any input
    change (here the tie-break seed) changes the digest, so B starts
    fresh instead of diverging silently."""
    import tpusim.io.storage as storage

    nodes, pods = _driver_inputs()
    real_save = storage.save_checkpoint
    saves = []

    def killing_save(*a, **k):
        path = real_save(*a, **k)
        saves.append(path)
        raise KeyboardInterrupt("simulated preemption")

    storage.save_checkpoint = killing_save
    try:
        with pytest.raises(KeyboardInterrupt):
            _run_driver(nodes, pods, 10, str(tmp_path), seed=42)
    finally:
        storage.save_checkpoint = real_save
    assert os.listdir(tmp_path)

    sim, _ = _run_driver(nodes, pods, 10, str(tmp_path), seed=43)
    assert not any("[Checkpoint] resumed" in l for l in sim.log.lines)


@pytest.mark.slow
def test_mesh_chunked_matches_plain(tmp_path):
    """The shard engine's gather-to-host snapshot: a mesh replay with
    checkpointing on matches both its own unsegmented run and the
    single-device engine bit-for-bit. resume-smoke only (ISSUE 17
    tier-1 buyback): tier-1 keeps the single-device kill/resume pin;
    the mesh==flat equivalence itself is pinned by the engine suites."""
    nodes, pods = _driver_inputs()
    _, r0 = _run_driver(nodes, pods, 0, "")
    _, r1 = _run_driver(nodes, pods, 0, "", mesh=4)
    _, r2 = _run_driver(nodes, pods, 9, str(tmp_path), mesh=4)
    _assert_equal(r0, r1)
    _assert_equal(r0, r2)


@pytest.mark.slow
def test_openb_prefix_resume(tmp_path):
    """Kill/resume bit-identity on real trace data (openb prefix), pinned
    against the unsegmented replay — the openb half of the acceptance
    criterion."""
    from tpusim.io.trace import load_node_csv, load_pod_csv

    node_csv = os.path.join(REPO, "data/csv/openb_node_list_gpu_node.csv")
    pod_csv = os.path.join(REPO, "data/csv/openb_pod_list_default.csv")
    if not (os.path.isfile(node_csv) and os.path.isfile(pod_csv)):
        pytest.skip("openb traces not present")
    nodes = load_node_csv(node_csv)[:200]
    pods = load_pod_csv(pod_csv)[:120]
    _, r0 = _run_driver(nodes, pods, 0, "")

    import tpusim.io.storage as storage

    real_save = storage.save_checkpoint
    state = {"n": 0}

    def killing_save(*a, **k):
        path = real_save(*a, **k)
        state["n"] += 1
        if state["n"] == 2:  # die after the SECOND checkpoint lands
            raise KeyboardInterrupt("simulated preemption")
        return path

    storage.save_checkpoint = killing_save
    try:
        with pytest.raises(KeyboardInterrupt):
            _run_driver(nodes, pods, 30, str(tmp_path))
    finally:
        storage.save_checkpoint = real_save

    sim, r2 = _run_driver(nodes, pods, 30, str(tmp_path))
    assert any("[Checkpoint] resumed replay" in l for l in sim.log.lines)
    _assert_equal(r0, r2)
    for a, b in zip(r0.metrics, r2.metrics):
        assert np.array_equal(np.asarray(a), np.asarray(b))
