"""Seed-batched execution (driver.schedule_pods_batch / run_batch) must give
each seed exactly what a standalone run gives: same placements, device
masks, final state, unscheduled lists, and reference-format log content
(metric float rows may differ in last-ulp reduce order, which the log's
fixed-precision formatting absorbs)."""

import numpy as np
import pytest

from tpusim.io.trace import NodeRow, PodRow
from tpusim.sim.driver import Simulator, SimulatorConfig, run_batch
from tpusim.sim.typical import TypicalPodsConfig


def _mk_cluster(rng):
    return [
        NodeRow(
            f"n{i:03d}", 32000, 131072, int(g), "V100M16" if g else ""
        )
        for i, g in enumerate(rng.choice([0, 2, 4, 8], 16))
    ]


def _mk_pods(rng, n=40):
    out = []
    for i in range(n):
        gpu = int(rng.choice([0, 1, 2]))
        milli = 1000 if gpu > 1 else int(rng.choice([0, 300, 500, 1000]))
        if gpu == 0:
            milli = 0
        out.append(
            PodRow(f"p{i:04d}", int(rng.choice([1000, 2000, 4000])), 2048,
                   gpu, milli)
        )
    return out


def _cfg(seed, policies=(("FGDScore", 1000),), gpu_sel="FGDScore",
         report=True, shuffle=True):
    return SimulatorConfig(
        policies=policies,
        gpu_sel_method=gpu_sel,
        shuffle_pod=shuffle,
        tuning_ratio=1.2,
        tuning_seed=seed,
        seed=seed,
        report_per_event=report,
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
    )


@pytest.mark.parametrize(
    "policies,gpu_sel",
    [
        ((("FGDScore", 1000),), "FGDScore"),
        # tier-1 trim, ISSUE 16: these two ride resume-smoke
        pytest.param((("BestFitScore", 1000),), "best",
                     marks=pytest.mark.slow),
        pytest.param((("RandomScore", 1000),), "random",  # sequential path
                     marks=pytest.mark.slow),
    ],
    ids=["fgd", "bestfit", "random"],
)
def test_batch_matches_single_runs(policies, gpu_sel):
    rng = np.random.default_rng(5)
    nodes = _mk_cluster(rng)
    pods = _mk_pods(rng)
    seeds = [42, 43, 44]

    singles = []
    for s in seeds:
        sim = Simulator(nodes, _cfg(s, policies, gpu_sel))
        sim.set_workload_pods(pods)
        sim.run()
        sim.finish()
        singles.append((sim.last_result, sim.log.dump()))

    batch_sims = []
    for s in seeds:
        sim = Simulator(nodes, _cfg(s, policies, gpu_sel))
        sim.set_workload_pods(pods)
        batch_sims.append(sim)
    results = run_batch(batch_sims)
    for sim in batch_sims:
        sim.finish()

    for (single, slog), sim, res in zip(singles, batch_sims, results):
        assert np.array_equal(single.placed_node, res.placed_node)
        assert np.array_equal(single.dev_mask, res.dev_mask)
        for a, b in zip(single.state, res.state):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        assert len(single.unscheduled_pods) == len(res.unscheduled_pods)
        assert [u.pod.name for u in single.unscheduled_pods] == [
            u.pod.name for u in res.unscheduled_pods
        ]
        assert np.array_equal(single.creation_rank, res.creation_rank)
        # the reference-format logs must match line-for-line: fixed-precision
        # formatting absorbs last-ulp float differences from vmapped reduces
        assert slog == sim.log.dump()


def test_batch_rejects_mixed_configs():
    rng = np.random.default_rng(9)
    nodes = _mk_cluster(rng)
    pods = _mk_pods(rng, 12)
    a = Simulator(nodes, _cfg(42))
    b = Simulator(
        nodes, _cfg(43, policies=(("BestFitScore", 1000),), gpu_sel="best")
    )
    a.set_workload_pods(pods)
    b.set_workload_pods(pods)
    with pytest.raises(ValueError, match="same-config"):
        run_batch([a, b])


def test_batch_no_report_mode():
    rng = np.random.default_rng(11)
    nodes = _mk_cluster(rng)
    pods = _mk_pods(rng, 30)
    seeds = [7, 8]
    singles = []
    for s in seeds:
        sim = Simulator(nodes, _cfg(s, report=False))
        sim.set_workload_pods(pods)
        sim.run()
        singles.append(sim.last_result)
    sims = []
    for s in seeds:
        sim = Simulator(nodes, _cfg(s, report=False))
        sim.set_workload_pods(pods)
        sims.append(sim)
    results = run_batch(sims)
    for single, res in zip(singles, results):
        assert np.array_equal(single.placed_node, res.placed_node)
