"""Data-prep converters (tpusim.io.data_prep): CSV → YAML → ingest must
reproduce the scheduling-relevant PodRow/NodeRow fields of direct CSV
ingestion (ref tools being re-created: data/pod_csv_to_yaml.py,
data/prepare_input.sh, node_yaml/)."""

import csv
import os

import pytest

from tpusim.io.data_prep import node_csv_to_yaml, pod_csv_to_yaml, prepare_input
from tpusim.io.k8s_yaml import load_objects, node_from_k8s, pod_from_k8s
from tpusim.io.trace import load_node_csv, load_pod_csv

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
POD_CSV = os.path.join(REPO, "data/csv/openb_pod_list_gpuspec10.csv")
NODE_CSV = os.path.join(REPO, "data/csv/openb_node_list_gpu_node.csv")

needs_traces = pytest.mark.skipif(
    not (os.path.isfile(POD_CSV) and os.path.isfile(NODE_CSV)),
    reason="openb traces not present",
)


@needs_traces
def test_pod_csv_yaml_roundtrip(tmp_path):
    """CSV → YAML → pod_from_k8s equals load_pod_csv on every
    scheduling-relevant field, including the creation/deletion times the
    reference converter drops (pod_csv_to_yaml.py:117-118). A 600-row
    prefix of the openb gpuspec10 list covers every column/annotation
    shape the full file does (tier-1 trim, ISSUE 14: the full-list
    round-trip cost ~21 s for no added coverage)."""
    prefix_csv = tmp_path / "pods_prefix.csv"
    with open(POD_CSV) as f:
        head = [next(f) for _ in range(601)]
    prefix_csv.write_text("".join(head))
    out = pod_csv_to_yaml(str(prefix_csv), tmp_path / "pods.yaml")
    via_yaml = [pod_from_k8s(o) for o in load_objects([str(out)])]
    direct = load_pod_csv(str(prefix_csv))
    assert len(via_yaml) == len(direct)
    for y, d in zip(via_yaml, direct):
        assert y.name == f"paib-gpu/{d.name}"
        assert (y.cpu_milli, y.memory_mib) == (d.cpu_milli, d.memory_mib)
        assert (y.num_gpu, y.gpu_milli, y.gpu_spec) == (
            d.num_gpu, d.gpu_milli, d.gpu_spec,
        )
        assert (y.creation_time, y.deletion_time) == (
            d.creation_time, d.deletion_time,
        )


@needs_traces
def test_node_csv_yaml_roundtrip(tmp_path):
    out = node_csv_to_yaml(NODE_CSV, tmp_path / "nodes.yaml")
    via_yaml = [node_from_k8s(o) for o in load_objects([str(out)])]
    direct = load_node_csv(NODE_CSV)
    assert len(via_yaml) == len(direct)
    for y, d in zip(via_yaml, direct):
        assert (y.name, y.cpu_milli, y.memory_mib, y.gpu, y.model) == (
            d.name, d.cpu_milli, d.memory_mib, d.gpu, d.model,
        )


def test_prepare_input_layout(tmp_path):
    """prepare_input builds one folder per pod trace, each holding the
    trace's pod YAML + the shared node YAML (prepare_input.sh layout)."""
    csv_dir = tmp_path / "csv"
    csv_dir.mkdir()
    with open(csv_dir / "openb_node_list_gpu_node.csv", "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["sn", "cpu_milli", "memory_mib", "gpu", "model"])
        w.writerow(["n0", 32000, 131072, 2, "V100M16"])
    for trace in ("openb_pod_list_a", "openb_pod_list_b"):
        with open(csv_dir / f"{trace}.csv", "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(
                ["name", "cpu_milli", "memory_mib", "num_gpu", "gpu_milli",
                 "gpu_spec", "qos", "pod_phase", "creation_time",
                 "deletion_time", "scheduled_time"]
            )
            w.writerow(["p0", 4000, 8192, 1, 500, "", "LS", "Running", 0, 0, 0])

    made = prepare_input(csv_dir, tmp_path / "input")
    assert [m.name for m in made] == ["openb_pod_list_a", "openb_pod_list_b"]
    for m in made:
        assert (m / f"{m.name}.yaml").is_file()
        assert (m / "openb_node_list_gpu_node.yaml").is_file()
        objs = load_objects(
            [str(m / f"{m.name}.yaml"),
             str(m / "openb_node_list_gpu_node.yaml")]
        )
        kinds = sorted(o["kind"] for o in objs)
        assert kinds == ["Node", "Pod"]


@needs_traces
def test_prepared_input_drives_apply(tmp_path):
    """The generated cluster-config directory must run end-to-end through
    the Applier (the consumer the reference's prepare_input.sh feeds)."""
    import io as _io

    import yaml as _yaml

    from tpusim.apply import Applier, ApplyOptions

    csv_dir = tmp_path / "csv"
    csv_dir.mkdir()
    # a tiny slice of the real traces keeps the end-to-end run fast
    with open(NODE_CSV) as f:
        rows = f.readlines()
    (csv_dir / "openb_node_list_gpu_node.csv").write_text(
        "".join(rows[:9])
    )
    with open(POD_CSV) as f:
        rows = f.readlines()
    (csv_dir / "openb_pod_list_tiny.csv").write_text("".join(rows[:13]))

    made = prepare_input(csv_dir, tmp_path / "input")
    cr = {
        "apiVersion": "simon/v1alpha1",
        "kind": "Config",
        "metadata": {"name": "prep"},
        "spec": {"cluster": {"customConfig": str(made[0])}},
    }
    cr_path = tmp_path / "cc.yaml"
    cr_path.write_text(_yaml.dump(cr))
    out = _io.StringIO()
    Applier(
        ApplyOptions(simon_config=str(cr_path), extended_resources=["gpu"])
    ).run(out=out)
    assert "unscheduled pods" in out.getvalue()


@needs_traces
def test_trace_stats_cli(capsys):
    """data/trace_stats.py (the reference's two stats notebooks as a CLI)
    must reproduce the notebook's headline numbers on the trace it uses:
    gpushare60's GPU-sharing request share is ~60% by construction."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "trace_stats", os.path.join(REPO, "data/trace_stats.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main([os.path.join(REPO, "data/csv/openb_pod_list_gpushare60.csv"),
              NODE_CSV])
    out = capsys.readouterr().out
    assert "Share-GPU" in out and "60.01%" in out
    assert "8152 pods" in out
    # node side: 1213 GPU nodes, G2 is the 8-GPU workhorse
    assert "1213 nodes" in out and "G2" in out
