"""The SLO plane: metrics history, burn-rate alerting, /events cursor
pagination, batched takeover recovery (ISSUE 20).

All host-side and fake-clocked — no HTTP servers, no device dispatch,
no sleeps. The live end-to-end acceptance (real fleet, induced fork
regression firing a page that resolves under recovery traffic, breaker
trip, kill -9 takeover splicing /query history) is gate.slo_smoke
(`make slo-smoke`).

Covered here:
  1. TSDB ring mechanics: bucket means, non-finite rejection, tier
     selection at the downsampling boundaries, retention pruning,
     latest() freshness;
  2. snapshot persistence: write -> adopt continuity, local-wins
     collisions, torn-file rejection;
  3. TsdbApp /query round-trips, hostile label values included
     (quotes, backslashes, newlines survive verbatim — only the
     Prometheus TEXT rendering escapes);
  4. the alert rule engine: threshold fire/resolve hysteresis,
     multi-window burn-rate AND semantics, staleness, transitions
     landing as kind=alert records in a VERIFYING audit chain,
     compose_health wrapping, rule loading/validation;
  5. the per-completion latency event feed (latency_samples_since) and
     the native /metrics latency summary rendering;
  6. /events cursor pagination (audit.tail `after` + the service's
     limit/after/next_after contract);
  7. batched standby-promotion recovery: many persisted specs re-admit
     through ONE submit_many pass, one batch audit record, full-queue
     leftover accounting.
"""

import io
import json
import urllib.parse

import numpy as np
import pytest

from tpusim.io.trace import NodeRow, PodRow
from tpusim.obs import alerts as obs_alerts
from tpusim.obs import audit as obs_audit
from tpusim.obs import tsdb as obs_tsdb
from tpusim.obs.emitters import latency_summary_lines
from tpusim.svc import jobs as svc_jobs
from tpusim.svc.api import JobService, recover_pending_jobs
from tpusim.svc.batcher import JobQueue
from tpusim.svc.worker import TraceRef

FAM = [["FGDScore", 1000], ["BestFitScore", 500]]
T0 = 1_700_000_000.0  # fake-clock epoch; every test drives `now`


def _mk_cluster(rng, n=12):
    return [
        NodeRow(f"n{i:03d}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4], n))
    ]


def _mk_pods(rng, n=20):
    out = []
    for i in range(n):
        gpu = int(rng.choice([0, 1]))
        out.append(
            PodRow(f"p{i:04d}", 1000, 2048, gpu, 500 if gpu else 0)
        )
    return out


@pytest.fixture(scope="module")
def trace():
    rng = np.random.default_rng(7)
    nodes, pods = _mk_cluster(rng), _mk_pods(rng)
    return TraceRef(
        "default", nodes, pods, svc_jobs.trace_digest(nodes, pods)
    )


# ---------------------------------------------------------------------------
# 1. TSDB ring mechanics
# ---------------------------------------------------------------------------


def test_tsdb_bucket_mean_and_nonfinite():
    db = obs_tsdb.TSDB(tiers=((1.0, 10), (5.0, 10)))
    # two samples in the SAME 1s bucket merge to their mean
    assert db.ingest([("m", None, 2.0)], now=T0 + 0.1) == 1
    assert db.ingest([("m", None, 4.0)], now=T0 + 0.6) == 1
    # non-finite and non-numeric samples are rejected, not stored
    assert db.ingest(
        [("m", None, float("nan")), ("m", None, float("inf")),
         ("m", None, "bogus")], now=T0 + 0.7,
    ) == 0
    (s,) = db.query("m", since=T0 - 5, now=T0 + 1)
    assert s["points"] == [[float(int(T0)), 3.0]]


def test_tsdb_tier_selection_at_retention_boundary():
    # fine: 1s x 10 (reaches 10s back), coarse: 5s x 100
    db = obs_tsdb.TSDB(tiers=((1.0, 10), (5.0, 100)))
    base = float(int(T0 / 5) * 5)  # align to the coarse bucket grid
    for i in range(10):
        db.ingest([("m", None, float(i))], now=base + i + 0.5)
    now = base + 9.5
    # a window the fine tier covers -> 1s resolution
    (fine,) = db.query("m", since=now - 8, now=now)
    assert fine["step_s"] == 1.0 and len(fine["points"]) >= 8
    # a window past the fine tier's retention -> the coarse tier, and
    # each coarse point is the MEAN of its five 1s samples
    (coarse,) = db.query("m", since=now - 60, now=now)
    assert coarse["step_s"] == 5.0
    assert coarse["points"][0] == [base, 2.0]  # mean(0..4)
    # an explicit step >= 5 forces the coarse tier even in-window
    (forced,) = db.query("m", since=now - 8, step=5.0, now=now)
    assert forced["step_s"] == 5.0


def test_tsdb_retention_prunes_fine_tier():
    db = obs_tsdb.TSDB(tiers=((1.0, 5), (10.0, 5)))
    for i in range(20):
        db.ingest([("m", None, 1.0)], now=T0 + i)
    (s,) = db.query("m", since=0, now=T0 + 19)
    assert len(s["points"]) <= 5
    assert s["points"][0][0] >= T0 + 15  # oldest buckets pruned


def test_tsdb_latest_and_staleness():
    db = obs_tsdb.TSDB(tiers=((1.0, 900),))
    db.ingest([("m", {"k": "a"}, 7.0)], now=T0)
    # since=0 means EVERYTHING — latest() depends on that
    ((labels, t, v),) = db.latest("m", now=T0 + 5)
    assert labels == {"k": "a"} and v == 7.0
    # stale series drop out of latest() past within_s
    assert db.latest("m", within_s=3.0, now=T0 + 5) == []
    assert db.latest("m", within_s=30.0, now=T0 + 5)


# ---------------------------------------------------------------------------
# 2. snapshot persistence
# ---------------------------------------------------------------------------


def test_snapshot_adopt_splices_history(tmp_path):
    art = str(tmp_path)
    a = obs_tsdb.TSDB(tiers=((1.0, 100),))
    for i in range(5):
        a.ingest([("m", None, float(i))], now=T0 + i)
    a.write_snapshot(art, now=T0 + 5)

    b = obs_tsdb.TSDB(tiers=((1.0, 100),))
    # the adopter has its own newer samples AND one colliding bucket
    b.ingest([("m", None, 100.0)], now=T0 + 4)   # collision: local wins
    b.ingest([("m", None, 200.0)], now=T0 + 10)
    adopted = b.adopt(art)
    assert adopted == 4  # buckets T0..T0+3; the T0+4 collision skipped
    (s,) = b.query("m", since=T0 - 1, now=T0 + 11)
    ts = [t for t, _ in s["points"]]
    assert ts == sorted(ts) and len(ts) == len(set(ts))
    vals = dict(s["points"])
    assert vals[float(int(T0 + 4))] == 100.0   # the adopter's bucket won
    assert vals[float(int(T0))] == 0.0         # history spliced in
    assert vals[float(int(T0 + 10))] == 200.0  # fresh samples intact


def test_snapshot_missing_and_torn(tmp_path):
    art = str(tmp_path)
    db = obs_tsdb.TSDB()
    assert db.adopt(art) == 0  # no snapshot = start blind, not crash
    db.ingest([("m", None, 1.0)], now=T0)
    path = db.write_snapshot(art, now=T0)
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw.replace(b'"m"', b'"x"', 1))  # edit -> digest breaks
    with pytest.raises(ValueError):
        obs_tsdb.TSDB().adopt(art)


# ---------------------------------------------------------------------------
# 3. the /query HTTP surface (TsdbApp.handle, no server)
# ---------------------------------------------------------------------------


def _get(app, path, **params):
    pairs = []
    for k, v in params.items():
        for vv in (v if isinstance(v, list) else [v]):
            pairs.append((k, vv))
    code, _, body = app.handle("GET", path, b"",
                               query=urllib.parse.urlencode(pairs))
    return code, json.loads(body.decode())


def test_query_endpoint_roundtrip_and_discovery():
    db = obs_tsdb.TSDB()
    db.ingest([("tpusim_queue_depth", None, 3.0)])
    app = obs_tsdb.TsdbApp(db)
    code, doc = _get(app, "/query", name="tpusim_queue_depth",
                     since="-60")
    assert code == 200 and doc["series"][0]["points"]
    # no name -> the discovery document
    code, doc = _get(app, "/query")
    assert code == 200
    assert doc["names"][0]["name"] == "tpusim_queue_depth"
    # malformed label / numbers -> 400, never a stack trace
    assert _get(app, "/query", name="m", label="nosep")[0] == 400
    assert _get(app, "/query", name="m", since="soon")[0] == 400
    # /alerts with no engine -> an empty document, not 404
    code, doc = _get(app, "/alerts")
    assert code == 200 and doc["firing"] == []


def test_query_hostile_label_roundtrip():
    hostile = 'we"ird\\na\nme'
    db = obs_tsdb.TSDB()
    # real-clock ingest: TsdbApp anchors relative `since` at time.time()
    db.ingest([("m", {"worker": hostile}, 1.0)])
    app = obs_tsdb.TsdbApp(db)
    code, doc = _get(app, "/query", name="m",
                     label=f"worker={hostile}", since="-60")
    # ingest/query keep hostile values VERBATIM (only the Prometheus
    # text rendering escapes) and the urlencoded filter still matches
    assert code == 200 and len(doc["series"]) == 1
    assert doc["series"][0]["labels"]["worker"] == hostile


# ---------------------------------------------------------------------------
# 4. the alert rule engine
# ---------------------------------------------------------------------------


def _threshold_rule(**over):
    rule = {
        "name": "sat", "type": "threshold", "severity": "ticket",
        "metric": "m", "op": ">=", "value": 0.9,
        "for_s": 5.0, "clear_for_s": 5.0,
    }
    rule.update(over)
    return rule


def _burn_rule(**over):
    rule = {
        "name": "burn", "type": "burn_rate", "severity": "page",
        "metric": "lat", "label": {"kind": "fork"},
        "objective": 2.0, "op": ">", "budget": 0.25,
        "windows": [{"window_s": 10.0, "burn": 2.0},
                    {"window_s": 40.0, "burn": 1.0}],
        "clear_for_s": 5.0,
    }
    rule.update(over)
    return rule


def test_threshold_fire_and_resolve_hysteresis():
    db = obs_tsdb.TSDB()
    eng = obs_alerts.AlertEngine(db, rules=[_threshold_rule()])
    # breach must SUSTAIN for_s before firing — a one-tick spike is ok
    db.ingest([("m", None, 0.95)], now=T0)
    assert eng.evaluate(now=T0) == []
    db.ingest([("m", None, 0.95)], now=T0 + 4)
    assert eng.evaluate(now=T0 + 4) == []        # 4s < for_s
    db.ingest([("m", None, 0.95)], now=T0 + 6)
    (t,) = eng.evaluate(now=T0 + 6)              # 6s >= for_s -> fires
    assert t["state"] == "firing" and t["alert"] == "sat"
    assert [f["alert"] for f in eng.firing()] == ["sat"]
    # clearing must sustain clear_for_s too (hysteresis both ways)
    db.ingest([("m", None, 0.1)], now=T0 + 8)
    assert eng.evaluate(now=T0 + 8) == []
    db.ingest([("m", None, 0.95)], now=T0 + 10)  # flap: breach again
    assert eng.evaluate(now=T0 + 10) == []       # still firing, no dup
    db.ingest([("m", None, 0.1)], now=T0 + 12)
    eng.evaluate(now=T0 + 12)
    db.ingest([("m", None, 0.1)], now=T0 + 18)
    (t,) = eng.evaluate(now=T0 + 18)             # clear held 6s >= 5s
    assert t["state"] == "resolved"
    assert eng.firing() == []


def test_threshold_stale_series_resolves():
    db = obs_tsdb.TSDB()
    eng = obs_alerts.AlertEngine(
        db, rules=[_threshold_rule(for_s=0.0, clear_for_s=0.0,
                                   staleness_s=10.0)]
    )
    db.ingest([("m", None, 1.0)], now=T0)
    (t,) = eng.evaluate(now=T0)
    assert t["state"] == "firing"
    # the series goes silent: past staleness it stops asserting and
    # the alert resolves rather than pinning the last value forever
    (t,) = eng.evaluate(now=T0 + 60)
    assert t["state"] == "resolved"


def test_burn_rate_needs_all_windows():
    db = obs_tsdb.TSDB()
    eng = obs_alerts.AlertEngine(db, rules=[_burn_rule()])
    lbl = {"kind": "fork"}
    # 35 good samples, then a short 5-sample breach burst
    for i in range(35):
        db.ingest([("lat", lbl, 0.1)], now=T0 + i)
    for i in range(5):
        db.ingest([("lat", lbl, 9.0)], now=T0 + 35 + i)
    # fast window [33,43]: 5 breach of 7 (0.71 >= need 0.5, burning);
    # slow window [3,43]: 5 breach of 37 (0.14 < need 0.25) -> a short
    # spike alone can NOT page
    assert eng.evaluate(now=T0 + 43) == []
    st = eng._state["burn"]["detail"]["windows"]
    assert st[0]["burning"] and not st[1]["burning"]
    # keep breaching until the SLOW window crosses its need too
    trans = []
    for i in range(25):
        db.ingest([("lat", lbl, 9.0)], now=T0 + 44 + i)
        trans += eng.evaluate(now=T0 + 44 + i)
    assert any(t["state"] == "firing" for t in trans)
    # recovery: good samples displace both windows -> resolves after
    # clear_for_s, WITH traffic still flowing
    trans = []
    for i in range(60):
        db.ingest([("lat", lbl, 0.1)], now=T0 + 70 + i)
        trans += eng.evaluate(now=T0 + 70 + i)
    assert any(t["state"] == "resolved" for t in trans)


def test_burn_rate_empty_window_is_not_burning():
    db = obs_tsdb.TSDB()
    eng = obs_alerts.AlertEngine(db, rules=[_burn_rule()])
    # no data at all: a burn rule needs EVENTS to burn budget
    assert eng.evaluate(now=T0) == []
    assert eng.firing() == []


def test_alert_transitions_chain_in_audit(tmp_path):
    art = str(tmp_path)
    db = obs_tsdb.TSDB()
    audit = obs_audit.AuditLog(art, process="test")
    eng = obs_alerts.AlertEngine(
        db, rules=[_threshold_rule(for_s=0.0, clear_for_s=0.0)],
        audit=audit,
    )
    db.ingest([("m", None, 1.0)], now=T0)
    eng.evaluate(now=T0)
    db.ingest([("m", None, 0.0)], now=T0 + 1)
    eng.evaluate(now=T0 + 1)
    # both transitions are records in a chain that VERIFIES
    assert obs_audit.verify(art) == 2
    recs = obs_audit.tail(art, kind="alert")
    assert [(r["alert"], r["state"]) for r in recs] == [
        ("sat", "firing"), ("sat", "resolved")
    ]
    assert all(r["kind"] == obs_audit.KIND_ALERT for r in recs)
    assert recs[0]["severity"] == "ticket"


def test_compose_health_wraps_not_replaces():
    db = obs_tsdb.TSDB()
    eng = obs_alerts.AlertEngine(
        db, rules=[_threshold_rule(severity="page", for_s=0.0)]
    )
    hook = eng.compose_health(lambda: (True, {"fleet": "fine"}))
    ok, extra = hook()
    assert ok and extra["fleet"] == "fine" and extra["alerts_page"] == []
    db.ingest([("m", None, 1.0)], now=T0)
    eng.evaluate(now=T0)
    ok, extra = hook()
    assert not ok and extra["alerts_page"] == ["sat"]
    assert extra["fleet"] == "fine"  # the wrapped hook still speaks
    # a page must not HIDE a dead fleet: prior hook's verdict is ANDed
    hook2 = eng.compose_health(lambda: (False, {"fleet": "dead"}))
    ok2, extra2 = hook2()
    assert not ok2 and extra2["fleet"] == "dead"


def test_load_rules_merge_override_and_validation(tmp_path):
    # no file -> the built-ins
    names = [r["name"] for r in obs_alerts.load_rules()]
    assert "fork-p99-burn" in names and "breaker-open" in names
    # file rules OVERRIDE same-named defaults, defaults fill the rest
    p = tmp_path / "slo.json"
    p.write_text(json.dumps([dict(
        obs_alerts.DEFAULT_RULES[0], objective=9.0)]))
    rules = obs_alerts.load_rules(str(p))
    mine = next(r for r in rules if r["name"] == "fork-p99-burn")
    assert mine["objective"] == 9.0
    assert len(rules) == len(obs_alerts.DEFAULT_RULES)
    # {"defaults": false} drops the built-ins
    p.write_text(json.dumps(
        {"defaults": False, "rules": [_threshold_rule()]}))
    assert [r["name"] for r in obs_alerts.load_rules(str(p))] == ["sat"]
    # duplicates and malformed rules fail AT LOAD, naming the problem
    p.write_text(json.dumps([_threshold_rule(), _threshold_rule()]))
    with pytest.raises(ValueError, match="duplicate"):
        obs_alerts.load_rules(str(p))
    for bad, msg in [
        (_threshold_rule(severity="sev1"), "severity"),
        (_threshold_rule(op="=~"), "op"),
        ({"name": "x", "type": "bogus", "metric": "m"}, "type"),
        (_burn_rule(budget=2.0), "budget"),
        (_burn_rule(windows=[]), "windows"),
        (dict(_threshold_rule(), value=None) and
         {k: v for k, v in _threshold_rule().items() if k != "value"},
         "value"),
    ]:
        with pytest.raises(ValueError, match=msg):
            obs_alerts.validate_rule(bad)


# ---------------------------------------------------------------------------
# 5. the latency event feed + the /metrics summary rendering
# ---------------------------------------------------------------------------


def _spec(i=0):
    return svc_jobs.validate_job(
        {"policies": FAM, "weights": [1000 + i, 500], "seed": 42}
    )


def test_latency_samples_since_cursor(trace):
    queue = JobQueue(maxsize=8, lane_width=2)
    cursors = {}
    assert queue.latency_samples_since(cursors) == {}
    j1 = queue.submit(_spec(1), "d1")
    j2 = queue.submit(_spec(2), "d2")
    queue.mark_done(j1, {"ok": 1})
    out = queue.latency_samples_since(cursors)
    assert list(out) == ["plain"] and len(out["plain"]) == 1
    # the cursor advanced: the same completion is never re-served
    assert queue.latency_samples_since(cursors) == {}
    queue.mark_done(j2, {"ok": 1})
    out = queue.latency_samples_since(cursors)
    assert len(out["plain"]) == 1
    # a foreign cursor dict starts from zero and sees everything
    assert len(queue.latency_samples_since({})["plain"]) == 2


def test_latency_summary_exposition_lines():
    lat = {
        "fork": {"count": 5, "p50_s": 0.01, "p99_s": 0.5,
                 "adjusted_p50_s": 0.01, "adjusted_p99_s": 0.4},
        'we"ird': {"count": 1, "p50_s": 1.0, "p99_s": 1.0},
    }
    text = "\n".join(latency_summary_lines(lat))
    assert "# TYPE tpusim_queue_latency_seconds summary" in text
    assert ('tpusim_queue_latency_seconds{kind="fork",quantile="0.99"} '
            "0.5") in text
    assert 'tpusim_queue_latency_seconds_count{kind="fork"} 5' in text
    assert ('tpusim_queue_latency_adjusted_seconds{kind="fork",'
            'quantile="0.99"} 0.4') in text
    # hostile kind values are ESCAPED in the text rendering
    assert 'kind="we\\"ird"' in text


# ---------------------------------------------------------------------------
# 6. /events cursor pagination
# ---------------------------------------------------------------------------


def test_audit_tail_cursor_semantics(tmp_path):
    art = str(tmp_path)
    log = obs_audit.AuditLog(art, process="test")
    for i in range(7):
        log.emit("steal", job=f"j{i}")
    # classic tail: newest n, oldest first
    tail = obs_audit.tail(art, n=3)
    assert [r["job"] for r in tail] == ["j4", "j5", "j6"]
    assert [r["seq"] for r in tail] == [5, 6, 7]
    # with a cursor the window flips to FORWARD pagination: the oldest
    # n past the cursor, so a poller never skips records
    page = obs_audit.tail(art, n=3, after=2)
    assert [r["seq"] for r in page] == [3, 4, 5]
    page = obs_audit.tail(art, n=3, after=5)
    assert [r["seq"] for r in page] == [6, 7]
    assert obs_audit.tail(art, n=3, after=7) == []


def test_events_endpoint_cursor(tmp_path, trace):
    art = str(tmp_path)
    queue = JobQueue(maxsize=8, lane_width=2)
    service = JobService(queue, None, {"default": trace}, art)
    log = obs_audit.AuditLog(art, process="test")
    for i in range(5):
        log.emit("steal", job=f"j{i}")

    def get(query):
        code, _, body = service._get_events(query)
        return code, json.loads(body.decode())

    code, doc = get("limit=2")
    assert code == 200 and doc["n"] == 2
    assert doc["next_after"] == 5  # tail window: newest records
    code, doc = get("after=2&limit=2")
    assert [e["seq"] for e in doc["events"]] == [3, 4]
    assert doc["next_after"] == 4
    code, doc = get(f"after={doc['next_after']}&limit=500")
    assert [e["seq"] for e in doc["events"]] == [5]
    # drained: the cursor echoes back instead of regressing to 0
    code, doc = get("after=5")
    assert doc["events"] == [] and doc["next_after"] == 5
    assert get("after=bogus")[0] == 400


# ---------------------------------------------------------------------------
# 7. batched takeover recovery
# ---------------------------------------------------------------------------


def _persist_specs(art, trace, n):
    digests = []
    for i in range(n):
        doc = {"policies": FAM, "weights": [1000 + i, 500], "seed": 42}
        spec = svc_jobs.validate_job(doc)
        d = svc_jobs.job_digest(spec, trace.digest)
        svc_jobs.write_job_spec(art, d, doc)
        digests.append(d)
    return digests


def test_recovery_batches_many_queued_jobs(tmp_path, trace):
    # the takeover-with-many-queued-jobs path: 60 persisted specs
    # re-admit through ONE submit_many pass with ONE audit record
    art = str(tmp_path)
    digests = _persist_specs(art, trace, 60)
    queue = JobQueue(maxsize=128, lane_width=2)
    service = JobService(queue, None, {"default": trace}, art)
    service.audit = obs_audit.AuditLog(art, process="test")
    out = io.StringIO()
    assert recover_pending_jobs(service, out=out) == 60
    assert queue.stats()["depth"] == 60
    with queue._cond:
        queued = [j.digest for j in queue._queue]
    assert queued == sorted(digests)  # pending_job_specs order (sorted)
    # every job got a trace id minted for the flight recorder
    assert all(service.trace_of(d) for d in digests)
    recs = obs_audit.tail(art, kind="requeue")
    assert len(recs) == 1 and recs[0]["n"] == 60
    assert len(recs[0]["jobs"]) == 16  # bounded digest sample


def test_recovery_full_queue_leaves_leftovers(tmp_path, trace):
    art = str(tmp_path)
    _persist_specs(art, trace, 12)
    queue = JobQueue(maxsize=8, lane_width=2)
    service = JobService(queue, None, {"default": trace}, art)
    out = io.StringIO()
    n = recover_pending_jobs(service, out=out)
    assert n == 8 and queue.stats()["depth"] == 8
    assert "4 spec(s) left" in out.getvalue()


def test_recovery_skips_malformed_and_unknown_trace(tmp_path, trace):
    art = str(tmp_path)
    _persist_specs(art, trace, 2)
    # a spec naming a trace this coordinator does not host: skipped
    # with a note, the REST of the batch still recovers
    doc = {"trace": "gone", "policies": FAM, "weights": [1, 2],
           "seed": 1}
    spec = svc_jobs.validate_job(doc)
    svc_jobs.write_job_spec(
        art, svc_jobs.job_digest(spec, "deadbeef"), doc)
    queue = JobQueue(maxsize=16, lane_width=2)
    service = JobService(queue, None, {"default": trace}, art)
    out = io.StringIO()
    assert recover_pending_jobs(service, out=out) == 2
    assert "skipping unrecoverable job" in out.getvalue()


def test_adopt_history_resumes_paused_sampler(tmp_path, trace):
    # the promotion half: adopt_history() splices the predecessor's
    # snapshot and UNPAUSES the sampler (never started = still paused)
    art = str(tmp_path)
    pred = obs_tsdb.TSDB()
    pred.ingest([("tpusim_queue_depth", None, 3.0)], now=T0)
    pred.write_snapshot(art, now=T0)
    queue = JobQueue(maxsize=8, lane_width=2)
    service = JobService(queue, None, {"default": trace}, art)
    service.tsdb = obs_tsdb.TSDB()
    service.sampler = obs_tsdb.MetricsSampler(
        service.tsdb, lambda now=None: [], paused=True)
    out = io.StringIO()
    assert service.adopt_history(out=out) == 2  # one bucket per tier
    assert not service.sampler.paused
    assert service.tsdb.query("tpusim_queue_depth", now=T0 + 5)
    # a TORN snapshot is refused loudly but sampling still resumes
    service2 = JobService(queue, None, {"default": trace}, art)
    service2.tsdb = obs_tsdb.TSDB()
    path = obs_tsdb.tsdb_snapshot_path(art)
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[:-10])
    service2.sampler = obs_tsdb.MetricsSampler(
        service2.tsdb, lambda now=None: [], paused=True)
    assert service2.adopt_history(out=out) == 0
    assert not service2.sampler.paused
    assert "refusing torn/edited tsdb snapshot" in out.getvalue()
