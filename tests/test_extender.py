"""Scheduler extenders (tpusim.sim.extender): the k8s HTTP extender
contract — filter subsetting, weighted prioritize scaled into the plugin
range, managedResources interest gating, ignorable-failure policy — driven
against a live stub extender server (ref: vendored core/extender.go +
generic_scheduler.go:520-560; pass-through at simulator.go:196)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import jax
import numpy as np
import pytest

from tpusim.config.scheduler import (
    SchedulerConfigError,
    parse_scheduler_config,
)
from tpusim.io.trace import NodeRow, PodRow
from tpusim.sim.driver import Simulator, SimulatorConfig
from tpusim.sim.extender import ExtenderConfig
from tpusim.sim.typical import TypicalPodsConfig


class _StubExtender(BaseHTTPRequestHandler):
    """Scriptable extender: class attrs control behavior per test."""

    reject_nodes = set()  # names the filter drops
    favorite = None  # prioritize: this node gets score 10, others 0
    fail_filter = False
    calls = []

    def do_POST(self):
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        type(self).calls.append((self.path, body))
        if self.path.endswith("/filter"):
            if type(self).fail_filter:
                self.send_response(500)
                self.end_headers()
                return
            names = body.get("nodenames")
            if names is None:
                names = [
                    it["metadata"]["name"] for it in body["nodes"]["items"]
                ]
            keep = [n for n in names if n not in type(self).reject_nodes]
            resp = (
                {"nodenames": keep}
                if body.get("nodenames") is not None
                else {"nodes": {"items": [
                    {"metadata": {"name": n}} for n in keep
                ]}}
            )
        else:  # prioritize
            names = body.get("nodenames") or [
                it["metadata"]["name"] for it in body["nodes"]["items"]
            ]
            resp = [
                {"host": n, "score": 10 if n == type(self).favorite else 0}
                for n in names
            ]
        data = json.dumps(resp).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):
        pass


@pytest.fixture
def extender_server():
    httpd = HTTPServer(("127.0.0.1", 0), _StubExtender)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    _StubExtender.reject_nodes = set()
    _StubExtender.favorite = None
    _StubExtender.fail_filter = False
    _StubExtender.calls = []
    yield f"http://127.0.0.1:{httpd.server_port}"
    httpd.shutdown()


def _cluster():
    # two identical nodes: without extender input, node-0 wins every
    # tie-break (rank = identity for seed-free configs)
    return [
        NodeRow("node-0", 32000, 131072, 4, "V100M16"),
        NodeRow("node-1", 32000, 131072, 4, "V100M16"),
    ]


def _pods(n=4):
    return [PodRow(f"p{i}", 4000, 4096, 1, 500) for i in range(n)]


def _run(url, n_pods=4, **ext_kw):
    cfg = SimulatorConfig(
        policies=(("BestFitScore", 1000),),
        gpu_sel_method="best",
        seed=0,
        report_per_event=True,
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
        extenders=(
            ExtenderConfig(
                url_prefix=url, filter_verb="filter",
                prioritize_verb="prioritize", **ext_kw,
            ),
        ),
    )
    sim = Simulator(_cluster(), cfg)
    sim.set_workload_pods(_pods(n_pods))
    res = sim.run()
    assert sim._last_engine == "extender"
    return sim, res


def test_extender_filter_excludes_node(extender_server):
    """A filter-rejected node must never receive a pod even when the
    plugin scores prefer it."""
    _StubExtender.reject_nodes = {"node-0"}
    sim, res = _run(extender_server)
    assert set(res.placed_node.tolist()) == {1}
    # both verbs were exercised
    verbs = {p.rsplit("/", 1)[-1] for p, _ in _StubExtender.calls}
    assert verbs == {"filter", "prioritize"}


def test_extender_prioritize_steers_selection(extender_server):
    """Max extender priority (10) × weight × (100/10 scale) beats the
    plugin-score delta between two near-equal nodes."""
    _StubExtender.favorite = "node-1"
    sim, res = _run(extender_server, weight=100)
    assert set(res.placed_node.tolist()) == {1}


def test_extender_noop_matches_sequential_engine(extender_server):
    """With a pass-through extender the host loop must reproduce the
    sequential engine bit-for-bit (same kernels, same key discipline)."""
    sim, res = _run(extender_server)

    plain = SimulatorConfig(
        policies=(("BestFitScore", 1000),), gpu_sel_method="best", seed=0,
        report_per_event=True, engine="sequential",
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
    )
    sim2 = Simulator(_cluster(), plain)
    sim2.set_workload_pods(_pods())
    res2 = sim2.run()
    np.testing.assert_array_equal(res.placed_node, res2.placed_node)
    np.testing.assert_array_equal(res.dev_mask, res2.dev_mask)
    # the analysis lanes see identical series too (shared post-pass)
    assert sim.event_reports[0]["series"].keys() == (
        sim2.event_reports[0]["series"].keys()
    )


def test_extender_nodecache_capable_payloads(extender_server):
    """nodeCacheCapable=True sends/receives NodeNames only."""
    _StubExtender.reject_nodes = {"node-0"}
    _run(extender_server, node_cache_capable=True)
    for _, body in _StubExtender.calls:
        assert "nodenames" in body and "nodes" not in body


def test_extender_failure_policy(extender_server):
    """A failing filter fails the cycle (pods unschedulable) unless the
    extender is ignorable (findNodesThatPassExtenders semantics)."""
    _StubExtender.fail_filter = True
    sim, res = _run(extender_server, n_pods=2)
    assert len(res.unscheduled_pods) == 2
    assert (res.placed_node == -1).all()

    _StubExtender.calls = []
    sim, res = _run(extender_server, n_pods=2, ignorable=True)
    assert not res.unscheduled_pods  # failure ignored, pods scheduled


def test_extender_managed_resources_gate(extender_server):
    """managedResources restricts the extender to pods requesting one of
    them (IsInterested): a CPU-only pod skips the GPU-managed extender."""
    _StubExtender.reject_nodes = {"node-0", "node-1"}  # would fail any pod
    cfg = SimulatorConfig(
        policies=(("BestFitScore", 1000),), gpu_sel_method="best", seed=0,
        report_per_event=False,
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
        extenders=(
            ExtenderConfig(
                url_prefix=extender_server, filter_verb="filter",
                managed_resources=("alibabacloud.com/gpu-milli",),
            ),
        ),
    )
    sim = Simulator(_cluster(), cfg)
    sim.set_workload_pods(
        [PodRow("cpu-pod", 4000, 4096, 0, 0), PodRow("gpu-pod", 4000, 4096, 1, 500)]
    )
    res = sim.run()
    names = {u.pod.name for u in res.unscheduled_pods}
    assert names == {"gpu-pod"}  # gated pod hit the rejecting extender
    assert res.placed_node[0] >= 0  # CPU pod skipped it entirely


def test_extender_config_parsing():
    doc = {
        "apiVersion": "kubescheduler.config.k8s.io/v1beta1",
        "kind": "KubeSchedulerConfiguration",
        "extenders": [
            {
                "urlPrefix": "http://ext:8080/scheduler",
                "filterVerb": "filter",
                "prioritizeVerb": "prioritize",
                "weight": 5,
                "nodeCacheCapable": True,
                "managedResources": [
                    {"name": "alibabacloud.com/gpu-milli",
                     "ignoredByScheduler": True}
                ],
            }
        ],
        "profiles": [
            {
                "schedulerName": "simon-scheduler",
                "plugins": {"score": {"enabled": [
                    {"name": "FGDScore", "weight": 1000}
                ]}},
            }
        ],
    }
    cfg = parse_scheduler_config(doc)
    (ext,) = cfg.extenders
    assert ext.url_prefix == "http://ext:8080/scheduler"
    assert ext.weight == 5 and ext.node_cache_capable
    assert ext.managed_resources == ("alibabacloud.com/gpu-milli",)

    doc["extenders"][0]["bindVerb"] = "bind"
    with pytest.raises(SchedulerConfigError, match="bindVerb"):
        parse_scheduler_config(doc)
    del doc["extenders"][0]["bindVerb"]
    doc["extenders"][0]["enableHTTPS"] = True
    with pytest.raises(SchedulerConfigError, match="enableHTTPS"):
        parse_scheduler_config(doc)
