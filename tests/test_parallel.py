"""Multi-chip sharding: the node-axis-sharded replay must be bit-identical
to the single-device replay (sharding is an execution detail, not semantics),
and padding rows must be inert."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusim.io.trace import tiebreak_rank
from tpusim.parallel import make_mesh, make_sharded_replay, pad_nodes, shard_state
from tpusim.policies import make_policy
from tpusim.sim.engine import EV_CREATE, EV_DELETE, make_replay
from tpusim.types import PodSpec, make_node_state, make_typical_pods


def _fixture(num_nodes=13, num_pods=24, seed=3):
    rng = np.random.default_rng(seed)
    state = make_node_state(
        cpu_cap=rng.choice([32000, 64000], num_nodes),
        mem_cap=np.full(num_nodes, 262144),
        gpu_cnt=rng.choice([0, 2, 4, 8], num_nodes),
        gpu_type=rng.integers(0, 3, num_nodes),
    )
    tp = make_typical_pods(
        [(4000, 500, 1, 0, 0.5), (8000, 1000, 2, 0, 0.3), (2000, 0, 0, 0, 0.2)]
    )
    pods = PodSpec(
        cpu=jnp.asarray(rng.choice([2000, 8000], num_pods).astype(np.int32)),
        mem=jnp.asarray(np.full(num_pods, 4096, np.int32)),
        gpu_milli=jnp.asarray(rng.choice([300, 1000], num_pods).astype(np.int32)),
        gpu_num=jnp.asarray(rng.choice([0, 1, 2], num_pods).astype(np.int32)),
        gpu_mask=jnp.zeros(num_pods, jnp.int32),
        pinned=jnp.full(num_pods, -1, jnp.int32),
    )
    kind = np.full(num_pods, EV_CREATE, np.int32)
    kind[5] = EV_DELETE  # delete of a never-placed pod is a no-op
    return state, tp, pods, jnp.asarray(kind), jnp.arange(num_pods, dtype=jnp.int32)


@pytest.mark.parametrize("policy", ["FGDScore", "BestFitScore"])
def test_sharded_replay_matches_single_device(policy):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    state, tp, pods, ev_kind, ev_pod = _fixture()
    rank = jnp.asarray(tiebreak_rank(state.num_nodes, seed=0))
    key = jax.random.PRNGKey(7)
    policies = [(make_policy(policy), 1000)]

    base = make_replay(policies, gpu_sel="best", report=True)(
        state, pods, ev_kind, ev_pod, tp, key, rank
    )

    mesh = make_mesh(8)
    pstate, prank = pad_nodes(state, rank, 8)
    pstate = shard_state(pstate, mesh)
    sharded = make_sharded_replay(policies, mesh, gpu_sel="best", report=True)(
        pstate, pods, ev_kind, ev_pod, tp, key, prank
    )

    np.testing.assert_array_equal(base.placed_node, sharded.placed_node)
    np.testing.assert_array_equal(base.dev_mask, sharded.dev_mask)
    n = state.num_nodes
    for a, b in zip(base.state, sharded.state):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b)[:n])
    np.testing.assert_allclose(
        np.asarray(base.metrics.frag_amounts),
        np.asarray(sharded.metrics.frag_amounts),
        rtol=1e-6,
    )
    # pad rows must be metric-inert too: usage/power identical
    np.testing.assert_array_equal(base.metrics.used_nodes, sharded.metrics.used_nodes)
    np.testing.assert_array_equal(
        base.metrics.used_cpu_milli, sharded.metrics.used_cpu_milli
    )
    np.testing.assert_allclose(
        np.asarray(base.metrics.power_cpu), np.asarray(sharded.metrics.power_cpu)
    )


def test_pad_nodes_inert():
    state, tp, pods, ev_kind, ev_pod = _fixture(num_nodes=5)
    rank = jnp.asarray(tiebreak_rank(5, seed=0))
    pstate, prank = pad_nodes(state, rank, 8)
    assert pstate.num_nodes == 8
    # pad rows fail the fit test for every pod (mem_left = -1 < any request)
    assert np.all(np.asarray(pstate.mem_left[5:]) == -1)
    assert np.all(np.asarray(pstate.cpu_left[5:]) == 0)
    assert np.all(np.asarray(prank[5:]) == np.iinfo(np.int32).max)
    # cluster aggregates unchanged
    assert int(pstate.gpu_cnt.sum()) == int(state.gpu_cnt.sum())
    assert int(pstate.cpu_cap.sum()) == int(state.cpu_cap.sum())


def test_sharded_table_replay_matches_unsharded():
    """The sharded table engine must reproduce the unsharded one bit-for-bit
    (and therefore the sequential oracle) on the virtual 8-device mesh."""
    import numpy as np

    from tests.fixtures import random_cluster, random_pods
    from tpusim.parallel import (
        make_mesh,
        make_sharded_table_replay,
        pad_nodes,
        shard_state,
    )
    from tpusim.policies import make_policy
    from tpusim.sim.table_engine import build_pod_types, make_table_replay

    rng = np.random.default_rng(41)
    state, tp = random_cluster(rng, num_nodes=21)
    pods = random_pods(rng, num_pods=40)
    types = build_pod_types(pods)
    ev_kind = jnp.zeros(40, jnp.int32)
    ev_pod = jnp.arange(40, dtype=jnp.int32)
    policies = [(make_policy("FGDScore"), 1000)]
    key = jax.random.PRNGKey(7)
    rank = jnp.asarray(tiebreak_rank(21, seed=3))

    plain = make_table_replay(policies, gpu_sel="FGDScore")
    r0 = plain(state, pods, types, ev_kind, ev_pod, tp, key, rank)

    mesh = make_mesh(8)
    pstate, prank = pad_nodes(state, rank, 8)
    pstate = shard_state(pstate, mesh)
    sharded = make_sharded_table_replay(policies, mesh, gpu_sel="FGDScore")
    r1 = sharded(pstate, pods, types, ev_kind, ev_pod, tp, key, prank)

    np.testing.assert_array_equal(
        np.asarray(r0.placed_node), np.asarray(r1.placed_node)
    )
    np.testing.assert_array_equal(np.asarray(r0.dev_mask), np.asarray(r1.dev_mask))
    n = state.num_nodes
    for a, b in zip(jax.tree.leaves(r0.state), jax.tree.leaves(r1.state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b)[:n])


@pytest.mark.parametrize(
    "policy,gpu_sel",
    [
        ("FGDScore", "FGDScore"),
        ("BestFitScore", "best"),
        # tier-1 trim, ISSUE 16: these two ride resume-smoke
        pytest.param("GpuPackingScore", "worst", marks=pytest.mark.slow),
        pytest.param("PWRScore", "PWRScore",  # global pwr normalization
                     marks=pytest.mark.slow),
    ],
    ids=lambda p: str(p),
)
def test_shardmap_replay_matches_unsharded(policy, gpu_sel):
    """The explicit-collective shard_map engine (parallel.shard_engine) must
    reproduce the unsharded table engine bit-for-bit on placements/state/
    telemetry across mesh sizes — and therefore (shared post-pass) produce
    byte-identical per-event report series."""
    from tests.fixtures import random_cluster, random_pods
    from tests.test_table_engine import _events_with_deletes
    from tpusim.parallel.shard_engine import make_shardmap_table_replay
    from tpusim.sim.metrics import compute_event_metrics
    from tpusim.sim.table_engine import build_pod_types, make_table_replay

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rng = np.random.default_rng(43)
    state, tp = random_cluster(rng, num_nodes=21)
    pods = random_pods(rng, num_pods=48)
    ev_kind, ev_pod = _events_with_deletes(48, rng)
    types = build_pod_types(pods)
    policies = [(make_policy(policy), 1000)]
    key = jax.random.PRNGKey(7)
    rank = jnp.asarray(tiebreak_rank(21, seed=3))

    plain = make_table_replay(policies, gpu_sel=gpu_sel)
    r0 = plain(state, pods, types, ev_kind, ev_pod, tp, key, rank)
    m0 = compute_event_metrics(
        state, pods, ev_kind, ev_pod, r0.event_node, r0.event_dev, tp
    )

    for n_dev in (2, 8):
        mesh = make_mesh(n_dev)
        pstate, prank = pad_nodes(state, rank, n_dev)
        pstate = shard_state(pstate, mesh)
        sharded = make_shardmap_table_replay(policies, mesh, gpu_sel=gpu_sel)
        r1 = sharded(pstate, pods, types, ev_kind, ev_pod, tp, key, prank)
        np.testing.assert_array_equal(
            np.asarray(r0.placed_node), np.asarray(r1.placed_node)
        )
        np.testing.assert_array_equal(
            np.asarray(r0.dev_mask), np.asarray(r1.dev_mask)
        )
        np.testing.assert_array_equal(
            np.asarray(r0.event_node), np.asarray(r1.event_node)
        )
        np.testing.assert_array_equal(
            np.asarray(r0.event_dev), np.asarray(r1.event_dev)
        )
        n = state.num_nodes
        for a, b in zip(jax.tree.leaves(r0.state), jax.tree.leaves(r1.state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b)[:n])
        # identical telemetry + metric-inert pad rows -> the shared
        # post-pass reconstructs the same report series: integer fields
        # exactly; the f32 init totals may rebracket with the extra zero
        # rows (within-configuration lanes stay byte-identical — the
        # driver always post-passes the state it replayed)
        m1 = compute_event_metrics(
            pstate, pods, ev_kind, ev_pod, r1.event_node, r1.event_dev, tp
        )
        for f, a0 in zip(m0._fields, m0):
            b0 = np.asarray(getattr(m1, f))
            if np.asarray(a0).dtype.kind == "f":
                np.testing.assert_allclose(
                    np.asarray(a0), b0, rtol=2e-5, atol=1e-2, err_msg=f
                )
            else:
                np.testing.assert_array_equal(np.asarray(a0), b0, err_msg=f)
