"""The learned scorer as a first-class policy (ISSUE 14,
tpusim.learn.policy / tpusim.learn.dataset).

Tier-1 slice (one tiny synthetic cluster, a handful of compiled
families):

  1. feature-kernel vocabulary: i32 scores inside [0, MAX_NODE_SCORE],
     DOWN flag semantics, make_policy resolution (singletons — the
     engine-cache identity contract), name validation;
  2. cross-engine bit-identity: a signed learned parameter vector
     replays identically on the sequential, flat-table, blocked-table,
     and shard_map engines — AND through checkpoint kill/resume — like
     any built-in, because theta IS the weight operand;
  3. explain attribution: the decision flight recorder's raw/norm
     columns become per-feature contributions whose weighted sum equals
     the recorded selectHost total exactly (format_explain enforces it);
  4. the signed artifact: round-trip, torn-file rejection, unknown
     features rejected, parse_policy_spec forms;
  5. dataset + imitation: teacher-forcing reproduces the teacher's
     feasible counts exactly, pairs/mining/tie discipline, and a small
     FGD log imitates back above chance with a perfect-frag fallback;
  6. sweep/service composition: run_sweep over a theta population is
     bit-identical per lane to standalone runs, and a `serve
     --policy-preset`-style preset answers submit jobs byte-identically
     to the artifact run locally.

The openb acceptance (>= 95% held-out top-1 imitation agreement, ES
strictly beating the FGD-equivalent default on the held-out objective,
one executable per tuning run) is slow-marked into `make resume-smoke`;
`make policy-smoke` (= gate --policy-only) runs the CI-sized version.
"""

import json
import os

import numpy as np
import pytest

from tpusim.io.trace import NodeRow, PodRow
from tpusim.learn.dataset import (
    TeacherReplay,
    imitate_with_mining,
    load_teacher_log,
)
from tpusim.learn.loop import ImitateConfig, project_theta, run_imitation
from tpusim.learn.policy import (
    BUCKETED_FEATURES,
    LINEAR_FEATURES,
    default_theta,
    learned_policies,
    load_policy_artifact,
    parse_policy_spec,
    policies_from_artifact,
    save_policy_artifact,
)
from tpusim.policies import is_policy_name, make_policy
from tpusim.sim.driver import Simulator, SimulatorConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

THETA = [700, -120, 45, 10, 80, -60, 33, 25, -200, 50]


def _mk_cluster(rng, n=14):
    return [
        NodeRow(f"n{i:03d}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], n))
    ]


def _mk_pods(rng, n=48):
    out = []
    for i in range(n):
        gpu = int(rng.choice([0, 1, 2]))
        milli = 1000 if gpu > 1 else int(rng.choice([0, 300, 500, 1000]))
        if gpu == 0:
            milli = 0
        out.append(
            PodRow(f"p{i:04d}", int(rng.choice([1000, 2000, 4000])), 2048,
                   gpu, milli)
        )
    return out


@pytest.fixture(scope="module")
def synth():
    rng = np.random.default_rng(5)
    return _mk_cluster(rng), _mk_pods(rng)


def _sim(nodes, pods, policies, **kw):
    kw.setdefault("gpu_sel_method", "best")
    kw.setdefault("seed", 7)
    kw.setdefault("report_per_event", False)
    sim = Simulator(nodes, SimulatorConfig(policies=tuple(policies), **kw))
    sim.set_workload_pods(list(pods))
    return sim


# ---------------------------------------------------------------------------
# 1. the feature vocabulary
# ---------------------------------------------------------------------------


def test_feature_kernels_vocabulary():
    """Every feature kernel emits i32 in [0, 100]; DOWN nodes read 0
    free everything + the down flag; kernels are singletons (the engine
    cache keys on object identity); names validate."""
    import jax.numpy as jnp

    from tpusim.constants import MAX_NODE_SCORE
    from tpusim.policies.base import ScoreContext
    from tpusim.types import make_node_state, make_pod
    from tests.fixtures import typical_pods_gpu

    state = make_node_state(
        cpu_cap=[32000, 64000, 16000],
        mem_cap=[131072, 131072, 65536],
        gpu_cnt=[4, 0, 8],
        gpu_type=[0, -1, 4],
    )
    # node 2 goes DOWN (the fault sentinel): mem_left = -1, gpu zeroed
    state = state._replace(
        mem_left=state.mem_left.at[2].set(-1),
        gpu_left=state.gpu_left.at[2].set(0),
    )
    pod = make_pod(cpu=1000, mem=2048, gpu_milli=500, gpu_num=1)
    ctx = ScoreContext(
        tp=typical_pods_gpu(), feasible=jnp.ones(3, bool),
        rng=__import__("jax").random.PRNGKey(0),
    )
    for feat in BUCKETED_FEATURES:
        name = f"LearnedScore[{feat}]"
        fn = make_policy(name)
        assert fn is make_policy(name)  # singleton
        assert fn.policy_name == name and fn.normalize == "none"
        assert is_policy_name(name)
        res = fn(state, pod, ctx)
        scores = np.asarray(res.raw_scores)
        assert scores.dtype == np.int32 and scores.shape == (3,)
        assert (scores >= 0).all() and (scores <= MAX_NODE_SCORE).all()
        if feat == "down":
            assert scores.tolist() == [0, 0, MAX_NODE_SCORE]
        if feat in ("free_gpu_pct", "free_mem_pct", "max_dev_free_pct"):
            assert scores[2] == 0  # DOWN node has nothing free
    assert not is_policy_name("LearnedScore[nope]")
    assert not is_policy_name("LearnedScore[")
    with pytest.raises(KeyError):
        make_policy("LearnedScore[nope]")
    # frag_delta IS the FGD frag gradient: identical raw rows
    fgd = make_policy("FGDScore")
    fd = make_policy("LearnedScore[frag_delta]")
    np.testing.assert_array_equal(
        np.asarray(fgd(state, pod, ctx).raw_scores),
        np.asarray(fd(state, pod, ctx).raw_scores),
    )


def test_learned_policies_validation():
    pairs = learned_policies(THETA)
    assert [n for n, _ in pairs] == [
        f"LearnedScore[{f}]" for f in LINEAR_FEATURES
    ]
    assert [w for _, w in pairs] == THETA
    assert default_theta(LINEAR_FEATURES)[0] == 1000
    with pytest.raises(ValueError, match="unknown learned feature"):
        learned_policies([1], features=("nope",))
    with pytest.raises(ValueError, match="entries for"):
        learned_policies([1, 2], features=LINEAR_FEATURES)
    with pytest.raises(ValueError, match="export bounds"):
        learned_policies([99999] + [0] * (len(LINEAR_FEATURES) - 1))


# ---------------------------------------------------------------------------
# 2. cross-engine bit-identity + kill/resume
# ---------------------------------------------------------------------------


def test_learned_four_engine_bit_identity(synth):
    """The acceptance pin: one signed theta replays bit-identically —
    placements, dev masks, counters — on all four engines, exactly like
    a built-in (theta is the weight operand; the tables hold feature
    rows)."""
    nodes, pods = synth
    pol = learned_policies(THETA)
    results = {}
    for label, kw in (
        ("sequential", dict(engine="sequential")),
        ("flat", dict(engine="table", block_size=-1)),
        ("blocked", dict(engine="table", block_size=4)),
        ("shard", dict(engine="auto", mesh=2)),
    ):
        res = _sim(nodes, pods, pol, **kw).run()
        results[label] = res
    ref = results["sequential"]
    assert int((np.asarray(ref.placed_node) >= 0).sum()) > 0
    for label, res in results.items():
        np.testing.assert_array_equal(
            np.asarray(ref.placed_node), np.asarray(res.placed_node), label
        )
        np.testing.assert_array_equal(
            np.asarray(ref.dev_mask), np.asarray(res.dev_mask), label
        )


@pytest.mark.slow  # tier-1 trim, ISSUE 16: rides resume-smoke
def test_learned_kill_resume_bit_identity(synth, tmp_path):
    """A checkpointed learned replay cut mid-trace resumes
    bit-identically (the carry embeds the feature tables + theta via
    the blocked summaries exactly like built-in weights)."""
    nodes, pods = synth
    pol = learned_policies(THETA)
    plain = _sim(nodes, pods, pol, engine="table").run()
    chunked = _sim(
        nodes, pods, pol, engine="table",
        checkpoint_every=7, checkpoint_dir=str(tmp_path),
    ).run()
    np.testing.assert_array_equal(
        np.asarray(plain.placed_node), np.asarray(chunked.placed_node)
    )
    np.testing.assert_array_equal(
        np.asarray(plain.dev_mask), np.asarray(chunked.dev_mask)
    )


# ---------------------------------------------------------------------------
# 3. explain attribution
# ---------------------------------------------------------------------------


@pytest.mark.slow  # tier-1 trim, ISSUE 16: rides resume-smoke
def test_explain_per_feature_attribution(synth, tmp_path):
    """`tpusim explain` renders per-FEATURE contribution rows whose
    weighted sum format_explain checks against the recorded selectHost
    total EXACTLY (it raises on any mismatch — so a passing render IS
    the attribution proof)."""
    from tpusim.obs import decisions as obs_dec

    nodes, pods = synth
    pol = learned_policies(THETA)
    sim = _sim(nodes, pods, pol, record_decisions=True)
    res = sim.run()
    path = str(tmp_path / "learned_dec.jsonl")
    obs_dec.write_decisions(
        path, res.decisions, policies=pol,
        meta=sim._telemetry_meta(), pod_names=[p.name for p in res.pods],
    )
    header, rows = obs_dec.read_decisions(path)
    ev = next(
        i for i, r in enumerate(rows)
        if r["kind"] == 0 and r["node"] >= 0
    )
    text = obs_dec.format_explain(header, rows, ev)
    assert "LearnedScore[frag_delta]" in text
    assert "== recorded total" in text
    # norm == raw for the learned family (normalize='none'): the raw
    # column IS the per-feature value the sum consumed
    assert rows[ev]["raw"] == rows[ev]["norm"]


# ---------------------------------------------------------------------------
# 4. the signed artifact
# ---------------------------------------------------------------------------


def test_artifact_roundtrip_and_torn_rejection(tmp_path):
    path = str(tmp_path / "pol.json")
    save_policy_artifact(path, THETA, meta={"note": "t"})
    feats, theta, meta = load_policy_artifact(path)
    assert feats == LINEAR_FEATURES and theta == THETA
    assert meta["note"] == "t"
    assert policies_from_artifact(path) == learned_policies(THETA)

    # parse_policy_spec forms
    assert parse_policy_spec(f"LearnedScore:{path}") == learned_policies(THETA)
    assert parse_policy_spec("learned") == learned_policies()
    assert parse_policy_spec("learned-bucketed") == learned_policies(
        features=BUCKETED_FEATURES
    )
    assert parse_policy_spec("FGDScore") == [("FGDScore", 1000)]
    with pytest.raises(ValueError, match="unknown --policy"):
        parse_policy_spec("nonsense")
    with pytest.raises(ValueError, match="not found"):
        parse_policy_spec("LearnedScore:/no/such/file.json")

    # a torn/edited artifact fails loudly
    with open(path) as f:
        lines = f.read().splitlines()
    doc = json.loads(lines[1])
    doc["theta"][0] += 1
    with open(path, "w") as f:
        f.write(lines[0] + "\n")
        f.write(json.dumps(doc, sort_keys=True, separators=(",", ":")))
        f.write("\n")
    with pytest.raises(ValueError, match="digest mismatch"):
        load_policy_artifact(path)

    # unknown features in an otherwise-signed artifact fail too
    bad = str(tmp_path / "bad.json")
    from tpusim.io import storage

    storage.write_signed_json(
        bad, {"schema": "tpusim-learned-policy/1"},
        {"features": ["nope"], "theta": [1], "meta": {}},
    )
    with pytest.raises(ValueError, match="unknown learned feature"):
        load_policy_artifact(bad)


# ---------------------------------------------------------------------------
# 5. dataset + imitation
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def teacher_log(synth, tmp_path_factory):
    """A recorded FGD teacher run over the synthetic trace (+ the
    prepared pod order the log describes)."""
    from tpusim.obs import decisions as obs_dec

    nodes, pods = synth
    sim = _sim(
        nodes, pods, (("FGDScore", 1000),), gpu_sel_method="FGDScore",
        seed=42, record_decisions=True,
    )
    res = sim.run()
    path = str(tmp_path_factory.mktemp("teach") / "teacher.jsonl")
    obs_dec.write_decisions(
        path, res.decisions, policies=[("FGDScore", 1000)],
        meta=sim._telemetry_meta(), pod_names=[p.name for p in res.pods],
    )
    return nodes, sim.prepare_pods(), path, res


def test_teacher_replay_and_imitation(teacher_log):
    """The dataset builder teacher-forces the exact recorded trajectory
    (feasible counts cross-checked per event), pure-frag theta agrees
    100% with an FGD teacher by construction, and the mining trainer
    recovers a high-agreement export from the pairs alone."""
    nodes, prep, path, _ = teacher_log
    header, rows = load_teacher_log(path)
    replay = TeacherReplay(nodes, prep, header, rows)

    # the FGD-equivalent theta reproduces the teacher argmax exactly
    pure = [1000 if f == "frag_delta" else 0 for f in LINEAR_FEATURES]
    rep = replay.agreement(pure)
    assert rep["matches"] == rep["creates"] > 0

    pairs = replay.pairs()
    assert pairs.pos.shape == pairs.neg.shape
    assert pairs.pos.shape[1] == len(LINEAR_FEATURES)
    assert pairs.pos.shape[0] > 0
    # strict pairs are separable by the frag axis with margin >= 1
    # (the teacher IS the frag gradient)
    strict = ~pairs.tie
    fd = LINEAR_FEATURES.index("frag_delta")
    assert (pairs.pos[strict, fd] > pairs.neg[strict, fd]).all()
    # tie pairs carry EQUAL teacher totals = equal frag values
    assert (pairs.pos[pairs.tie, fd] == pairs.neg[pairs.tie, fd]).all()

    cut = len(rows) - len(rows) // 5
    _, theta, hist = imitate_with_mining(
        replay, ImitateConfig(steps=600, lr=0.3, l2=1e-6),
        end_event=cut, rounds=4,
    )
    held = replay.agreement(theta, start_event=cut)
    assert held["agreement"] >= 0.75, (theta, hist, held)


def test_teacher_replay_rejects_wrong_trace(teacher_log):
    """Replaying a log against the WRONG workload fails the per-event
    feasible-count cross-check loudly instead of training on garbage."""
    nodes, prep, path, _ = teacher_log
    header, rows = load_teacher_log(path)
    # length mismatch fails immediately
    with pytest.raises(ValueError, match="wrong trace or prep"):
        TeacherReplay(nodes, prep[:-3], header, rows)
    # same length, different pods: the feasibility invariant trips
    rng = np.random.default_rng(99)
    other = _mk_pods(rng, len(prep))
    rep = TeacherReplay(nodes, other, header, rows)
    with pytest.raises(ValueError, match="feasible count"):
        rep.pairs()


def test_imitation_trainer_units():
    """project_theta fills the i32 bound symmetrically; the trainer
    separates a linearly-separable toy set; tie pairs pull weights off
    tie-breaking features."""
    assert project_theta([0.5, -0.25], 4000) == [4000, -2000]
    assert project_theta([0.0, 0.0]) == [0, 0]
    rng = np.random.default_rng(0)
    w_true = np.asarray([3.0, -2.0, 0.0])
    x = rng.normal(size=(300, 3)) * 50
    pos_better = (x @ w_true) > 0
    pos = np.where(pos_better[:, None], x, -x)
    neg = np.where(pos_better[:, None], -x, x)
    from tpusim.learn.dataset import ImitationPairs

    pairs = ImitationPairs(
        features=("a", "b", "c"), pos=pos, neg=neg,
        event=np.arange(300), tie=np.zeros(300, bool),
    )
    theta_f, theta = run_imitation(pairs, ImitateConfig(steps=400))
    z = (pos - neg) @ np.asarray(theta, float)
    assert (z > 0).mean() > 0.97
    # a tie-only feature gets suppressed
    tie = ImitationPairs(
        features=("a", "b", "c"),
        pos=np.tile([0.0, 0.0, 10.0], (100, 1)),
        neg=np.zeros((100, 3)),
        event=np.arange(100), tie=np.ones(100, bool),
    )
    from tpusim.learn.dataset import concat_pairs

    theta_f2, _ = run_imitation(concat_pairs([pairs, tie]),
                                ImitateConfig(steps=400))
    assert abs(theta_f2[2]) < 0.2 * max(abs(theta_f2[0]), abs(theta_f2[1]))


# ---------------------------------------------------------------------------
# 6. sweep + service composition
# ---------------------------------------------------------------------------


def test_learned_sweep_lane_vs_standalone(synth):
    """A theta POPULATION through run_sweep (the ES trainer's rollout
    surface): each lane bit-identical to the standalone run with that
    theta baked — the one-compile parameter-search contract."""
    nodes, pods = synth
    pol = learned_policies(THETA)
    sim = _sim(nodes, pods, pol)
    grid = np.stack([
        np.asarray(THETA, np.int32),
        np.asarray(default_theta(LINEAR_FEATURES), np.int32),
        np.asarray([-100, 50, 0, 0, 200, 0, -30, 10, 0, 0], np.int32),
    ])
    lanes = sim.run_sweep(grid, seeds=[7, 7, 7])
    assert len(lanes) == 3
    for i in (0, 2):
        single = _sim(
            nodes, pods,
            learned_policies([int(w) for w in grid[i]]),
        ).run()
        np.testing.assert_array_equal(
            lanes[i].placed_node, np.asarray(single.placed_node)
        )
    # distinct thetas genuinely diverge somewhere
    assert not np.array_equal(lanes[0].placed_node, lanes[2].placed_node)


@pytest.mark.slow  # tier-1 trim, ISSUE 16: rides resume-smoke
def test_policy_preset_answers_like_local(synth, tmp_path):
    """`serve --policy-preset` end-to-end (in-process): a submit job
    referencing the preset replays byte-identically to the artifact run
    locally; preset misuse fails loudly."""
    from tpusim.svc import jobs as svc_jobs
    from tpusim.svc.api import JobService
    from tpusim.svc.batcher import JobQueue
    from tpusim.svc.worker import TraceRef, Worker

    nodes, pods = synth
    art = str(tmp_path / "served.json")
    save_policy_artifact(art, THETA)
    presets = {"mypolicy": policies_from_artifact(art)}

    trace = TraceRef(
        "default", nodes, pods, svc_jobs.trace_digest(nodes, pods)
    )
    queue = JobQueue(maxsize=8, lane_width=4)
    worker = Worker(queue, {"default": trace}, str(tmp_path))
    service = JobService(
        queue, worker, {"default": trace}, str(tmp_path),
        policy_presets=presets,
    )

    resp = service.handle(
        "POST", "/jobs",
        json.dumps({"policy_preset": "mypolicy", "seed": 7}).encode(),
    )
    assert resp[0] == 202, resp
    job_id = json.loads(resp[2].decode())["id"]
    while True:
        batch = queue.next_batch(timeout=0)
        if not batch:
            break
        worker.run_batch(batch)
    code, _, body = service.handle(
        "GET", f"/jobs/{job_id}/result", b"")[:3]
    assert code == 200
    got = json.loads(body.decode())
    local = _sim(nodes, pods, policies_from_artifact(art)).run()
    np.testing.assert_array_equal(
        np.asarray(got["placed_node"]), np.asarray(local.placed_node)
    )
    # /queue lists the preset
    stats = json.loads(service.handle("GET", "/queue", b"")[2].decode())
    assert stats["policy_presets"] == ["mypolicy"]

    # unknown preset and preset+weights are 400s
    for doc, msg in (
        ({"policy_preset": "nope"}, "unknown policy preset"),
        ({"policy_preset": "mypolicy", "weights": [1] * 10},
         "excludes explicit"),
    ):
        code, _, body = service.handle(
            "POST", "/jobs", json.dumps(doc).encode())[:3]
        assert code == 400 and msg in body.decode()
    # a preset key reaching bare validation (no service) names the gap
    with pytest.raises(ValueError, match="expanded by the serving"):
        svc_jobs.validate_job({"policy_preset": "mypolicy"})


def test_tune_learned_zero_recompile(synth, tmp_path):
    """ES over the learned parameter vector = PR 8's loop verbatim: one
    compiled sweep executable across generations, signed log, artifact
    export via the tune CLI's learned branch."""
    from tpusim.learn import LocalRollout, TuneConfig, run_tune
    from tpusim.learn.rollout import make_family_sim

    nodes, pods = synth
    pol = learned_policies()
    sim = make_family_sim(nodes, pods, pol)
    backend = LocalRollout(sim, width=4)
    cfg = TuneConfig(algo="es", generations=2, popsize=4, sigma=300.0,
                     lr=400.0, seed=3, w_lo=-4000, w_hi=4000)
    result = run_tune(backend, pol, cfg, str(tmp_path / "log.jsonl"))
    # counts are read RELATIVE to what sibling tests compiled into the
    # process-global wrapper: a second tuning run over the same family
    # must add ZERO executables
    before = backend.executables()
    run_tune(backend, pol,
             TuneConfig(**{**cfg.__dict__, "seed": 4,
                           "objective": cfg.objective}),
             str(tmp_path / "log2.jsonl"))
    assert backend.executables() == before
    assert len(result.records) == 2
    assert len(result.best_weights) == len(LINEAR_FEATURES)
    # negative parameters survive the projection (the symmetric bounds)
    assert cfg.w_lo == -4000


# ---------------------------------------------------------------------------
# slow: the openb acceptance
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def openb_prefix():
    from tpusim.io.trace import load_node_csv, load_pod_csv

    nodes = load_node_csv(
        os.path.join(REPO, "data/csv/openb_node_list_gpu_node.csv")
    )
    pods = load_pod_csv(
        os.path.join(REPO, "data/csv/openb_pod_list_default.csv")
    )[:400]
    return nodes, pods


@pytest.mark.slow
def test_openb_imitation_acceptance(openb_prefix, tmp_path):
    """ISSUE 14 acceptance: imitation of an openb FGD decision log
    reaches >= 95% top-1 agreement on a held-out suffix."""
    from tpusim.obs import decisions as obs_dec

    nodes, pods = openb_prefix
    sim = _sim(
        nodes, pods, (("FGDScore", 1000),), gpu_sel_method="FGDScore",
        seed=42, record_decisions=True,
    )
    res = sim.run()
    path = str(tmp_path / "openb_teacher.jsonl")
    obs_dec.write_decisions(
        path, res.decisions, policies=[("FGDScore", 1000)],
        meta=sim._telemetry_meta(), pod_names=[p.name for p in res.pods],
    )
    header, rows = load_teacher_log(path)
    replay = TeacherReplay(nodes, sim.prepare_pods(), header, rows)
    cut = len(rows) - len(rows) // 5
    _, theta, hist = imitate_with_mining(
        replay, ImitateConfig(steps=1000, lr=0.3, l2=1e-6),
        end_event=cut, rounds=5,
    )
    held = replay.agreement(theta, start_event=cut)
    assert held["creates"] >= 50
    assert held["agreement"] >= 0.95, (theta, hist, held)


@pytest.mark.slow
def test_openb_es_beats_default(openb_prefix):
    """ISSUE 14 acceptance: ES-trained parameters strictly beat the
    FGD-equivalent default theta on the held-out objective (the PR 8
    holdout-report protocol), with one compiled executable after gen 1."""
    from tpusim.learn import (
        LocalRollout,
        ObjectiveConfig,
        TuneConfig,
        holdout_report,
        run_tune,
    )
    from tpusim.learn.rollout import make_family_sim

    nodes, pods = openb_prefix
    pol = learned_policies()
    n_train = len(pods) - len(pods) // 5
    train, held = pods[:n_train], pods[n_train:]
    sim = make_family_sim(nodes, train, pol)
    backend = LocalRollout(sim, width=8)
    cfg = TuneConfig(
        algo="es", generations=16, popsize=8, sigma=600.0, lr=500.0,
        seed=11, w_lo=-4000, w_hi=4000,
        objective=ObjectiveConfig(),
    )
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        result = run_tune(backend, pol, cfg, os.path.join(d, "log.jsonl"))
        # the zero-recompile hard check, read RELATIVE to whatever
        # sibling tests compiled into the process-global sweep wrapper
        # (the test_tune_learned_zero_recompile idiom): two more
        # generations must add NOTHING
        before = backend.executables()
        assert before >= 1
        run_tune(
            backend, pol,
            TuneConfig(algo="es", generations=2, popsize=8, sigma=600.0,
                       lr=500.0, seed=12, w_lo=-4000, w_hi=4000,
                       objective=ObjectiveConfig()),
            os.path.join(d, "log2.jsonl"),
        )
        assert backend.executables() == before
    eval_sim = make_family_sim(nodes, held, pol)
    report = holdout_report(
        eval_sim, pol, result.best_weights, objective=cfg.objective,
        eval_seed=cfg.eval_seed,
    )
    assert report["improvement"] > 0, report
