"""The in-scan fault plane (ISSUE 10; tpusim.sim.fault_lane): fault
schedules as scan/sweep operands with an in-carry retry queue.

Acceptance pins:
- scan-vs-segmented bit-identity under one seed (placements,
  DisruptionMetrics, final state) — `run_with_faults` became a thin
  wrapper over the in-scan lane and must reproduce the PR 2 host loop;
- engine invariance of the fault lane (sequential / flat table /
  blocked table / shard_map);
- kill/resume continuity of the retry-queue carry (run_chunk splits);
- retry-queue overflow -> terminal max-retries-exceeded;
- chaos-sweep lanes bit-identical to standalone runs per schedule;
- the crash-safety satellites: torn-checkpoint walk-back, svc job-spec
  persistence + restart recovery, graceful-drain 503s.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusim.io.trace import NodeRow, PodRow, build_events, pods_to_specs
from tpusim.sim import fault_lane
from tpusim.sim.driver import Simulator, SimulatorConfig
from tpusim.sim.engine import EV_EVICT, EV_NODE_FAIL, EV_NODE_RECOVER
from tpusim.sim.faults import FaultConfig, FaultEvent, generate_fault_schedule

CFG = dict(
    policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
    report_per_event=False,
)


def _sim(nodes, pods, **over):
    sim = Simulator(nodes, SimulatorConfig(**{**CFG, **over}))
    sim.set_workload_pods(pods)
    sim.set_typical_pods()
    return sim


def _nodes(n=2):
    return [
        NodeRow(f"host-{i}", 16000, 65536, 2, "V100M16") for i in range(n)
    ]


def _pods(n):
    return [PodRow(f"p{i}", 2000, 1024, 1, 500) for i in range(n)]


def _mixed_fcfg(seed=5):
    return FaultConfig(
        mtbf_events=3, mttr_events=4, evict_every_events=5, seed=seed,
        backoff_base=2, backoff_cap=8, max_retries=2,
    )


def _assert_same_run(ra, dma, rb, dmb, frag_tol=0.0):
    assert np.array_equal(ra.placed_node, rb.placed_node)
    assert np.array_equal(ra.dev_mask, rb.dev_mask)
    for f, (x, y) in zip(
        ra.state._fields,
        zip(jax.tree.leaves(ra.state), jax.tree.leaves(rb.state)),
    ):
        assert np.array_equal(np.asarray(x), np.asarray(y)), f
    a, b = dma.as_dict(), dmb.as_dict()
    for k in a:
        if isinstance(a[k], float):
            assert abs(a[k] - b[k]) <= frag_tol, (k, a[k], b[k])
        else:
            assert a[k] == b[k], (k, a[k], b[k])
    assert (dma.reschedule_latency_events == dmb.reschedule_latency_events)
    assert [u.reason for u in ra.unscheduled_pods] == [
        u.reason for u in rb.unscheduled_pods
    ]


# ---- the acceptance pin: scan == segmented host loop ----


@pytest.mark.slow  # pays BOTH the segmented and the in-scan fault
# compiles (~14s); resume-smoke runs it, tier-1 keeps the cheaper
# engine-invariance pin (ISSUE 16 budget buy-back)
def test_scan_equals_segmented_mixed_schedule():
    """run_with_faults (now the in-scan lane) is bit-identical to the
    PR 2 segmented path under one seed: an MTBF schedule with fails,
    recovers, AND random-victim evictions — placements, every
    DisruptionMetrics number (latency list included), final state, and
    the unscheduled reasons."""
    nodes, pods = _nodes(), _pods(6)
    fcfg = _mixed_fcfg()
    sa = _sim(nodes, pods, fault_mode="segments")
    ra = sa.schedule_pods_with_faults(pods, fault_cfg=fcfg)
    sb = _sim(nodes, pods, fault_mode="scan")
    rb = sb.schedule_pods_with_faults(pods, fault_cfg=fcfg)
    assert sb._last_engine.endswith("(fault lane)")
    _assert_same_run(ra, sa.last_disruption, rb, sb.last_disruption)
    # the scan lane narrates + reports like the host loop
    assert any("[Disruption]" in l for l in sb.log.lines)
    assert any("[Fault]" in l for l in sb.log.lines)
    assert any(k.startswith("disruption_") for k in sb.analysis_summary)


def _invariance_runs(overrides):
    nodes, pods = _nodes(), _pods(6)
    fcfg = _mixed_fcfg(seed=7)
    runs = []
    for over in overrides:
        sim = _sim(nodes, pods, fault_mode="scan", **over)
        res = sim.schedule_pods_with_faults(pods, fault_cfg=fcfg)
        runs.append((res, sim.last_disruption))
    for res, dm in runs[1:]:
        _assert_same_run(runs[0][0], runs[0][1], res, dm)


@pytest.mark.slow  # two fault-engine compiles (~11 s) — the ISSUE 19
# tier-1 buy-back trims it into resume-smoke beside the blocked case
def test_fault_lane_engine_invariant():
    """sequential vs flat-table fault lanes replay one schedule
    bit-identically (the shard engine is pinned separately; the
    blocked-table lane — a third engine compile — runs under
    resume-smoke: tier-1 trim, ISSUE 11 satellite)."""
    _invariance_runs((
        {"engine": "sequential"},
        {"engine": "table"},
    ))


@pytest.mark.slow  # compiles the blocked fault engine on top of the two
# the fast case pays for — resume-smoke runs it
def test_fault_lane_engine_invariant_blocked():
    """The blocked-table fault lane (block summaries + retry pops) joins
    the sequential/flat invariance set."""
    _invariance_runs((
        {"engine": "sequential"},
        {"engine": "table", "block_size": 2},
    ))


@pytest.mark.slow  # a third fault-engine compile (shard_map mesh);
# resume-smoke runs it (ISSUE 16 budget buy-back)
def test_fault_lane_shard_engine():
    """The shard_map fault lane: owner-masked row resets/requeues under
    a 2-device mesh match the segmented path (frag-delta list excepted —
    psum f32 cannot be bit-equal, so the shard build skips it). Three
    nodes on two devices exercises the mesh-padded node axis — pad rows
    must stay invisible to victims, down clocks, and the dark-capacity
    accounting."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    nodes, pods = _nodes(3), _pods(8)
    fcfg = _mixed_fcfg(seed=11)
    sa = _sim(nodes, pods, fault_mode="segments")
    ra = sa.schedule_pods_with_faults(pods, fault_cfg=fcfg)
    sb = _sim(nodes, pods, fault_mode="scan", mesh=2)
    rb = sb.schedule_pods_with_faults(pods, fault_cfg=fcfg)
    assert sb._last_engine.startswith("shard_map")
    assert np.array_equal(ra.placed_node, rb.placed_node)
    assert np.array_equal(ra.dev_mask, rb.dev_mask)
    a, b = sa.last_disruption.as_dict(), sb.last_disruption.as_dict()
    for k in a:
        if k.startswith("post_recovery"):
            continue
        assert a[k] == b[k], (k, a[k], b[k])
    for f, (x, y) in zip(
        ra.state._fields,
        zip(jax.tree.leaves(ra.state), jax.tree.leaves(rb.state)),
    ):
        assert np.array_equal(np.asarray(x), np.asarray(y)), f


# ---- retry-queue carry semantics ----


@pytest.mark.slow  # compiles per-cut chunk variants on top of the
# unsplit scan (~16s); resume-smoke runs it (ISSUE 16 budget buy-back)
def test_retry_carry_kill_resume_continuity():
    """Splitting the merged stream across run_chunk calls (the
    checkpoint surface) is bit-identical to one unsplit scan — the
    retry queue, attempts, dead list, and down clocks are carry leaves
    like everything else."""
    from tpusim.sim.table_engine import build_pod_types, make_table_replay

    nodes, pods = _nodes(), _pods(6)
    sim = _sim(nodes, pods)
    specs = pods_to_specs(pods, sim.node_index)
    ev_kind, ev_pod = build_events(pods, False)
    fcfg = _mixed_fcfg(seed=3)
    faults = generate_fault_schedule(len(nodes), len(ev_kind), fcfg)
    plan = fault_lane.compile_fault_plan(
        ev_kind, ev_pod, faults, fcfg, len(nodes), len(pods)
    )
    types = build_pod_types(specs)
    fn = make_table_replay(
        sim._policy_fns, gpu_sel="FGDScore", faults=True,
        fault_frag=plan.has_recover,
    )
    ops = fault_lane.FaultOps(
        pos=jnp.asarray(plan.pos), arg=jnp.asarray(plan.arg),
        aux=jnp.asarray(plan.aux), draws=jnp.asarray(plan.draws),
        params=jnp.asarray(plan.params),
        gcnt=jnp.asarray(sim.init_state.gpu_cnt),
    )
    fc0 = fault_lane.init_fault_carry(
        len(pods), len(nodes), plan.capacity
    )
    key = jax.random.PRNGKey(42)
    # an even-length merged-stream prefix on purpose: the two split
    # chunks below then have EQUAL length and share one compiled
    # executable instead of two (tier-1 trim, ISSUE 11 satellite);
    # a truncated stream is as valid a kill/resume subject as the full
    # one — both sides of the contract replay the same prefix
    k = int(plan.kind.shape[0]) // 2
    em2 = 2 * k
    whole = fn(
        sim.init_state, specs, types, jnp.asarray(plan.kind[:em2]),
        jnp.asarray(plan.idx[:em2]), sim.typical, key, sim.rank,
        fault_ops=ops._replace(
            pos=ops.pos[:em2], arg=ops.arg[:em2], aux=ops.aux[:em2]
        ),
        fault_carry0=fc0,
    )
    carry = fn.init_carry(
        sim.init_state, specs, types, sim.typical, key, sim.rank,
        fault_carry0=fc0,
    )
    for sl in (slice(0, k), slice(k, em2)):
        ops_sl = ops._replace(
            pos=ops.pos[sl], arg=ops.arg[sl], aux=ops.aux[sl]
        )
        carry, _ = fn.run_chunk(
            carry, specs, types, jnp.asarray(plan.kind[sl]),
            jnp.asarray(plan.idx[sl]), sim.typical, sim.rank,
            fault_ops=ops_sl,
        )
        carry = jax.tree.map(lambda a: jnp.asarray(np.asarray(a)), carry)
    state, placed, masks, failed = fn.finish(carry)
    assert np.array_equal(np.asarray(whole.placed_node), np.asarray(placed))
    assert np.array_equal(np.asarray(whole.dev_mask), np.asarray(masks))
    for x, y in zip(
        jax.tree.leaves(whole.fault_carry), jax.tree.leaves(carry[1])
    ):
        # fault_carry is trimmed on the one-shot result; compare on the
        # common prefix of each leaf
        xa, ya = np.asarray(x), np.asarray(y)
        assert np.array_equal(xa, ya[tuple(slice(0, s) for s in xa.shape)])


@pytest.mark.slow  # its capacity-1 merged stream is a one-off shape ->
# a dedicated ~5 s engine compile; resume-smoke runs it (tier-1 trim,
# ISSUE 11 satellite)
def test_retry_queue_overflow_goes_terminal():
    """An eviction wave past the static queue capacity goes terminal
    max-retries-exceeded (the documented divergence from the unbounded
    host heap) instead of silently corrupting."""
    nodes = [NodeRow("only", 16000, 65536, 4, "V100M16"),
             NodeRow("back", 2000, 1024, 0, "")]
    pods = _pods(3)  # all land on `only`
    fcfg = FaultConfig(backoff_base=2, backoff_cap=4, queue_capacity=1)
    sim = _sim(nodes, pods, fault_mode="scan")
    res = sim.schedule_pods_with_faults(
        pods, faults=[FaultEvent(pos=3, kind=EV_NODE_FAIL, node=0)],
        fault_cfg=fcfg,
    )
    dm = sim.last_disruption
    assert dm.evicted_pods == 3
    # one victim fits the queue; the overflow is terminal immediately
    assert dm.unscheduled_after_retries >= 2
    reasons = [u.reason for u in res.unscheduled_pods]
    assert reasons.count("max-retries-exceeded") >= 2


@pytest.mark.slow  # the auto-fallback leg compiles a segmented replay
# (~3 s) — ISSUE 19 tier-1 buy-back, resume-smoke runs it
def test_fault_mode_validation():
    nodes, pods = _nodes(), _pods(2)
    sim = _sim(nodes, pods, fault_mode="nope")
    with pytest.raises(ValueError, match="unknown fault_mode"):
        sim.schedule_pods_with_faults(pods, fault_cfg=FaultConfig())
    sim2 = _sim(nodes, pods, fault_mode="scan", report_per_event=True)
    with pytest.raises(ValueError, match="fault_mode='scan'"):
        sim2.schedule_pods_with_faults(pods, fault_cfg=FaultConfig())
    # auto + reporting falls back to the segmented path, not an error
    sim3 = _sim(nodes, pods, report_per_event=True)
    sim3.schedule_pods_with_faults(
        pods, faults=[FaultEvent(pos=1, kind=EV_EVICT, pod=0)],
        fault_cfg=FaultConfig(backoff_base=1, backoff_cap=1),
    )
    assert not sim3._last_engine.endswith("(fault lane)")


# ---- the chaos sweep ----


@pytest.mark.slow  # compiles the chaos engine plus 3 standalone lanes
def test_chaos_sweep_lanes_equal_standalone():
    """B fault schedules in ONE vmapped scan: every lane bit-identical
    (placements, DisruptionMetrics, state) to the standalone
    run_with_faults run with that schedule — the B>=1 slice of the
    acceptance criterion (`make chaos-smoke` runs the wider B=8 form
    with the zero-recompile check; tier-1 keeps the cheap rejection
    tests and the per-engine single-lane equivalences)."""
    nodes, pods = _nodes(4), _pods(8)
    specs = [
        FaultConfig(
            mtbf_events=4 + i, mttr_events=5, evict_every_events=6 - i,
            seed=100 + i, backoff_base=2, backoff_cap=8, max_retries=2,
        )
        for i in range(3)
    ]
    sim = _sim(nodes, pods)
    lanes = sim.run_sweep(
        np.asarray([[1000]] * 3, np.int32), seeds=[42] * 3, faults=specs
    )
    assert sim._last_engine.endswith("chaos sweep)")
    for i, lane in enumerate(lanes):
        solo = _sim(nodes, pods)
        res = solo.run_with_faults(fault_cfg=specs[i])
        dm = solo.last_disruption
        assert np.array_equal(res.placed_node, lane.placed_node), i
        a, b = dm.as_dict(), lane.disruption.as_dict()
        for k in a:
            if isinstance(a[k], float):
                assert abs(a[k] - b[k]) < 1e-9, (i, k)
            else:
                assert a[k] == b[k], (i, k)
        for x, y in zip(
            jax.tree.leaves(res.state), jax.tree.leaves(lane.state)
        ):
            assert np.array_equal(np.asarray(x), np.asarray(y))


def test_chaos_sweep_rejects_mismatched_lanes():
    nodes, pods = _nodes(), _pods(4)
    sim = _sim(nodes, pods)
    with pytest.raises(ValueError, match="fault_specs has"):
        sim.run_sweep(
            np.asarray([[1000]] * 2, np.int32),
            faults=[FaultConfig(mtbf_events=3)],
        )
    with pytest.raises(ValueError, match="FaultConfig"):
        sim.run_sweep(
            np.asarray([[1000]], np.int32), faults=["not-a-config"]
        )
    # the chaos x tune lift (ISSUE 12): combining tunes and faults is
    # legal now, but the per-lane lists must still line up
    with pytest.raises(ValueError, match="fault_specs has"):
        sim.run_sweep(
            np.asarray([[1000]] * 2, np.int32), tunes=[0.0, 0.1],
            faults=[FaultConfig(mtbf_events=3)],
        )


def test_load_faults_payload(tmp_path):
    from tpusim.apply import load_faults_payload

    path = tmp_path / "faults.json"
    path.write_text(json.dumps({
        "faults": [
            {"mtbf_events": 5, "seed": 1},
            {"mtbf_events": 7, "seed": 2, "queue_capacity": 16},
        ],
        "seeds": [1, 2],
    }))
    specs, weights, seeds = load_faults_payload(
        str(path), (("FGDScore", 1000),)
    )
    assert [s.mtbf_events for s in specs] == [5, 7]
    assert weights == [[1000], [1000]] and seeds == [1, 2]
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps([{"mtbf": 5}]))
    with pytest.raises(ValueError, match="unknown key"):
        load_faults_payload(str(bad), (("FGDScore", 1000),))


# ---- objective integration (ISSUE 10: disruption trainable) ----


def test_objective_disruption_term():
    from tpusim.learn.objective import ObjectiveConfig, scalarize

    terms = {
        "frag_gpu_milli": 0.0, "gpu_total_milli": 1000, "pods": 10,
        "unscheduled": 0, "disrupted": 2, "gpu_alloc_pct": 50.0,
    }
    base = scalarize(terms, ObjectiveConfig())
    hard = scalarize(terms, ObjectiveConfig(w_disrupt=1.0))
    assert hard == pytest.approx(base - 100.0 * 2 / 10)
    # w_disrupt = 0 keeps the pre-chaos log-header bytes
    assert ObjectiveConfig().canonical() == [1.0, 1.0, 1.0]
    assert ObjectiveConfig(w_disrupt=0.5).canonical() == [
        1.0, 1.0, 1.0, 0.5
    ]


# ---- crash-safety satellites ----


def test_torn_checkpoint_walkback(tmp_path):
    """A corrupt/truncated newest checkpoint is skipped (and deleted)
    with the resume continuing from the newest VALID one."""
    from tpusim.io import storage

    d = str(tmp_path)
    digest = "ab" * 32
    storage.save_checkpoint(d, digest, 2, {"x": np.arange(3)})
    storage.save_checkpoint(d, digest, 4, {"x": np.arange(3) + 1})
    torn = storage.checkpoint_path(d, digest, 4)
    with open(torn, "wb") as f:
        f.write(b"\x00truncated")
    skipped = []
    got = storage.load_valid_checkpoint(
        d, digest, on_skip=lambda p, e: skipped.append(p)
    )
    assert got is not None
    cursor, arrays, path = got
    assert cursor == 2 and np.array_equal(arrays["x"], np.arange(3))
    assert skipped == [torn] and not os.path.exists(torn)
    # a validate rejection also walks back (vocabulary drift reads as
    # corrupt)
    storage.save_checkpoint(d, digest, 6, {"y": np.arange(2)})

    def need_x(arrays):
        arrays["x"]

    got = storage.load_valid_checkpoint(d, digest, validate=need_x)
    assert got is not None and got[0] == 2
    # nothing valid at all -> None (fresh start), dir emptied of the junk
    storage.prune_checkpoints(d, digest, 10**9)
    assert storage.load_valid_checkpoint(d, digest) is None


@pytest.mark.slow  # boots two job servers and drains real batches
# (~6 s) — ISSUE 19 tier-1 buy-back, resume-smoke runs it
def test_svc_job_spec_persistence_and_recovery(tmp_path):
    """Accepted jobs persist as .job.json; a restarted service requeues
    every spec without a signed result (crash mid-batch no longer
    strands jobs in `running`)."""
    from tpusim.svc import jobs as svc_jobs
    from tpusim.svc.api import start_job_server
    from tpusim.svc.jobs import trace_digest
    from tpusim.svc.worker import TraceRef

    nodes, pods = _nodes(), _pods(4)
    trace = TraceRef("default", nodes, pods, trace_digest(nodes, pods))
    art = str(tmp_path)
    fam = [["FGDScore", 1000]]

    # first life: accept two jobs, run neither (start_worker=False =
    # the crash), then "restart" and observe both requeued
    srv, service, worker = start_job_server(
        art, {"default": trace}, listen=":0", start_worker=False,
        recover=False,
    )
    try:
        service.submit_payload({"policies": fam, "weights": [700]})
        service.submit_payload({"policies": fam, "weights": [900]})
        specs = svc_jobs.pending_job_specs(art)
        assert len(specs) == 2
    finally:
        worker.stop()
        srv.stop()

    srv2, service2, worker2 = start_job_server(
        art, {"default": trace}, listen=":0", start_worker=False,
        recover=True,
    )
    try:
        assert service2.queue.stats()["depth"] == 2
        # run the recovered batch synchronously; results persist and the
        # pending list drains
        batch = service2.queue.next_batch(timeout=1.0, linger_s=0.0)
        worker2.run_batch(batch)
        assert svc_jobs.pending_job_specs(art) == []
    finally:
        worker2.stop()
        srv2.stop()


def test_svc_graceful_drain(tmp_path):
    """begin_drain flips /healthz to 503 and POSTs answer 503 +
    Retry-After while the in-flight work finishes."""
    import urllib.error
    import urllib.request

    from tpusim.svc.api import start_job_server
    from tpusim.svc.jobs import trace_digest
    from tpusim.svc.worker import TraceRef

    nodes, pods = _nodes(), _pods(2)
    trace = TraceRef("default", nodes, pods, trace_digest(nodes, pods))
    srv, service, worker = start_job_server(
        str(tmp_path), {"default": trace}, listen=":0",
        start_worker=False, recover=False,
    )
    try:
        with urllib.request.urlopen(srv.url + "/healthz", timeout=10) as r:
            assert json.loads(r.read().decode())["ok"] is True
        srv.begin_drain()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url + "/healthz", timeout=10)
        assert ei.value.code == 503
        req = urllib.request.Request(
            srv.url + "/jobs", data=b"{}",
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After") == "2"
    finally:
        worker.stop()
        srv.stop()


@pytest.mark.slow  # compiles the chaos engine at the service lane width
def test_svc_fault_jobs_end_to_end(tmp_path):
    """Fault jobs through the POST path: a batch of `fault`-carrying
    jobs runs ONE compiled chaos sweep, results carry the
    DisruptionMetrics block, and each matches the standalone
    run_with_faults outcome for that schedule."""
    from tpusim.svc.api import start_job_server
    from tpusim.svc.jobs import trace_digest
    from tpusim.svc.worker import TraceRef

    nodes, pods = _nodes(4), _pods(8)
    trace = TraceRef("default", nodes, pods, trace_digest(nodes, pods))
    srv, service, worker = start_job_server(
        str(tmp_path), {"default": trace}, listen=":0",
        start_worker=False, recover=False, lane_width=4,
    )
    fam = [["FGDScore", 1000]]
    try:
        for i in range(2):
            service.submit_payload({
                "policies": fam,
                "fault": {"mtbf_events": 4.0 + i, "mttr_events": 5.0,
                          "seed": 100 + i, "backoff_base": 2,
                          "backoff_cap": 8, "max_retries": 2},
            })
        batch = service.queue.next_batch(timeout=1.0, linger_s=0.0)
        assert len(batch) == 2  # one family, one batch
        worker.run_batch(batch)
        for i, job in enumerate(batch):
            assert job.status == "done", job.error
            dis = job.result["disruption"]
            solo = _sim(nodes, pods, shuffle_pod=False, seed=42)
            res = solo.run_with_faults(
                fault_cfg=job.spec.fault_config()
            )
            assert dis == solo.last_disruption.as_dict()
            assert job.result["placed_node"] == [
                int(x) for x in res.placed_node
            ]
    finally:
        worker.stop()
        srv.stop()


def test_grid_fault_seeds_expansion():
    from tpusim.svc import jobs as svc_jobs

    docs = svc_jobs.jobs_from_grid({
        "weights": [[1000], [1000]],
        "fault": {"mtbf_events": 5.0, "mttr_events": 6.0},
        "fault_seeds": [1, 2],
    })
    assert [d["fault"]["seed"] for d in docs] == [1, 2]
    assert all(d["fault"]["mtbf_events"] == 5.0 for d in docs)
    with pytest.raises(ValueError, match="fault_seeds"):
        svc_jobs.jobs_from_grid(
            {"weights": [[1]], "fault_seeds": [1]}
        )
