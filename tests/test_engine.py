"""Scheduler-step and replay-engine tests: filter semantics, bind/unbind
accounting, event loop, and a small end-to-end driver run."""

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.constants import GPU_MODEL_IDS, MILLI
from tpusim.io.trace import NodeRow, PodRow
from tpusim.policies import make_policy
from tpusim.sim.driver import Simulator, SimulatorConfig
from tpusim.sim.engine import EV_CREATE, EV_DELETE, make_replay
from tpusim.sim.step import filter_nodes, schedule_one
from tpusim.types import make_node_state, make_pod, make_typical_pods


def two_nodes():
    return make_node_state(
        cpu_cap=[32000, 96000],
        mem_cap=[262144, 786432],
        gpu_cnt=[0, 4],
        gpu_type=[-1, GPU_MODEL_IDS["V100M16"]],
    )


TP = make_typical_pods([(1000, 500, 1, 0, 1.0)])


class TestFilter:
    def test_gpu_pod_rejects_cpu_node(self):
        st = two_nodes()
        pod = make_pod(cpu=1000, gpu_milli=500, gpu_num=1)
        np.testing.assert_array_equal(
            np.asarray(filter_nodes(st, pod)), [False, True]
        )

    def test_model_constraint(self):
        st = two_nodes()
        mask_a100 = 1 << GPU_MODEL_IDS["A100"]
        pod = make_pod(cpu=1000, gpu_milli=500, gpu_num=1, gpu_mask=mask_a100)
        assert not bool(filter_nodes(st, pod)[1])
        mask_v100 = 1 << GPU_MODEL_IDS["V100M16"]
        pod2 = make_pod(cpu=1000, gpu_milli=500, gpu_num=1, gpu_mask=mask_v100)
        assert bool(filter_nodes(st, pod2)[1])

    def test_cpu_fit(self):
        st = two_nodes()
        pod = make_pod(cpu=50000)
        np.testing.assert_array_equal(
            np.asarray(filter_nodes(st, pod)), [False, True]
        )

    def test_multi_gpu_fit(self):
        st = two_nodes()
        pod = make_pod(cpu=100, gpu_milli=1000, gpu_num=5)
        assert not bool(filter_nodes(st, pod)[1])
        pod4 = make_pod(cpu=100, gpu_milli=1000, gpu_num=4)
        assert bool(filter_nodes(st, pod4)[1])


class TestScheduleOne:
    def test_bind_updates_state(self):
        st = two_nodes()
        pod = make_pod(cpu=2000, mem=1024, gpu_milli=500, gpu_num=1)
        pols = [(make_policy("BestFitScore"), 1000)]
        new, pl = schedule_one(st, pod, jax.random.PRNGKey(0), pols, "best", TP)
        assert int(pl.node) == 1
        assert int(new.cpu_left[1]) == 96000 - 2000
        assert int(new.mem_left[1]) == 786432 - 1024
        assert int(np.asarray(new.gpu_left[1]).sum()) == 4000 - 500
        assert int(np.asarray(pl.dev_mask).sum()) == 1
        assert int(new.aff_cnt[1, 0]) == 1  # share class

    def test_unschedulable(self):
        st = two_nodes()
        pod = make_pod(cpu=100, gpu_milli=1000, gpu_num=8)
        pols = [(make_policy("BestFitScore"), 1000)]
        new, pl = schedule_one(st, pod, jax.random.PRNGKey(0), pols, "best", TP)
        assert int(pl.node) == -1
        np.testing.assert_array_equal(
            np.asarray(new.cpu_left), np.asarray(st.cpu_left)
        )
        np.testing.assert_array_equal(
            np.asarray(new.gpu_left), np.asarray(st.gpu_left)
        )

    def test_share_gpu_best_fit_device(self):
        st = two_nodes()
        st = st._replace(gpu_left=st.gpu_left.at[1, 0].set(600))
        pod = make_pod(cpu=100, gpu_milli=500, gpu_num=1)
        pols = [(make_policy("BestFitScore"), 1000)]
        new, pl = schedule_one(st, pod, jax.random.PRNGKey(0), pols, "best", TP)
        # tightest fitting device is d0 (600m left)
        assert bool(pl.dev_mask[0]) and int(np.asarray(pl.dev_mask).sum()) == 1
        assert int(new.gpu_left[1, 0]) == 100


class TestReplay:
    def test_create_then_delete_restores_state(self):
        st = two_nodes()
        pods = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            make_pod(cpu=2000, gpu_milli=500, gpu_num=1),
            make_pod(cpu=1000, gpu_milli=1000, gpu_num=2),
        )
        replay = make_replay([(make_policy("FGDScore"), 1000)], "FGDScore")
        ev_kind = jnp.asarray([EV_CREATE, EV_CREATE, EV_DELETE, EV_DELETE], jnp.int32)
        ev_pod = jnp.asarray([0, 1, 0, 1], jnp.int32)
        res = replay(st, pods, ev_kind, ev_pod, TP, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(
            np.asarray(res.state.cpu_left), np.asarray(st.cpu_left)
        )
        np.testing.assert_array_equal(
            np.asarray(res.state.gpu_left), np.asarray(st.gpu_left)
        )
        np.testing.assert_array_equal(
            np.asarray(res.state.aff_cnt), np.asarray(st.aff_cnt)
        )
        assert int(res.placed_node[0]) == -1  # deleted again
        # metrics rows exist for every event
        assert res.metrics.frag_amounts.shape == (4, 7)
        # arrived counters only accumulate on creations
        assert int(res.metrics.arrived_gpu_milli[-1]) == 500 + 2000
        assert int(res.metrics.arrived_cpu_milli[-1]) == 3000

    def test_failed_pod_leaves_no_trace(self):
        st = two_nodes()
        pods = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            make_pod(cpu=100, gpu_milli=1000, gpu_num=8),
        )
        replay = make_replay([(make_policy("BestFitScore"), 1000)], "best")
        res = replay(
            st, pods, jnp.asarray([EV_CREATE], jnp.int32),
            jnp.asarray([0], jnp.int32), TP, jax.random.PRNGKey(0),
        )
        assert bool(res.ever_failed[0])
        assert int(res.placed_node[0]) == -1
        np.testing.assert_array_equal(
            np.asarray(res.state.gpu_left), np.asarray(st.gpu_left)
        )


class TestDriverEndToEnd:
    def nodes(self):
        return [
            NodeRow("n-cpu", 32000, 262144, 0, ""),
            NodeRow("n-v100", 96000, 786432, 8, "V100M16"),
            NodeRow("n-a100", 96000, 786432, 4, "A100"),
        ]

    def pods(self):
        rows = []
        for i in range(4):
            rows.append(PodRow(f"p-share-{i}", 4000, 8192, 1, 500, "", creation_time=i))
        rows.append(PodRow("p-multi", 8000, 16384, 2, 1000, "", creation_time=10))
        rows.append(PodRow("p-a100", 4000, 8192, 1, 1000, "A100", creation_time=11))
        rows.append(PodRow("p-cpu", 2000, 4096, 0, 0, "", creation_time=12))
        return rows

    def test_fgd_run(self):
        sim = Simulator(self.nodes(), SimulatorConfig(policies=(("FGDScore", 1000),),
                                                      gpu_sel_method="FGDScore"))
        sim.set_workload_pods(self.pods())
        res = sim.run()
        assert not res.unscheduled_pods
        # A100-constrained pod must land on the A100 node (index 2)
        assert res.placed_node[5] == 2
        # placements conserve resources
        total_gpu_used = sum(
            p.total_gpu_milli for p, n in zip(res.pods, res.placed_node) if n >= 0
        )
        state_used = int(
            (np.asarray(sim.init_state.gpu_left) - res.state.gpu_left).sum()
        )
        assert state_used == total_gpu_used
        # log contract: per-event lines + 16-line analysis block present
        sim.finish()
        text = sim.log.dump()
        # two [Report] lines per create/delete event — the (origin) and
        # (bellman) variants (analysis.go:109-110; skip events emit none,
        # simulator.go:391-399; this workload has no skips)
        assert text.count("(origin)") == res.events
        assert text.count("(bellman)") == res.events
        assert "Cluster Analysis Results (InitSchedule)" in text
        assert "there are 0 unscheduled pods" in text

    def test_policy_sweep_all_run(self):
        for name in (
            "BestFitScore", "GpuPackingScore", "GpuClusteringScore",
            "RandomScore", "DotProductScore", "PWRScore", "Simon",
        ):
            gpu_sel = name if name in ("DotProductScore", "PWRScore") else "best"
            sim = Simulator(
                self.nodes(),
                SimulatorConfig(policies=((name, 1000),), gpu_sel_method=gpu_sel,
                                report_per_event=False),
            )
            sim.set_workload_pods(self.pods())
            res = sim.run()
            # policies may legitimately strand the A100-constrained pod by
            # filling the A100 node first; anything else must place
            assert all(
                u.pod.name == "p-a100" for u in res.unscheduled_pods
            ), name
            # placements conserve GPU milli
            used = sum(
                p.total_gpu_milli
                for p, n in zip(res.pods, res.placed_node)
                if n >= 0
            )
            state_used = int(
                (np.asarray(sim.init_state.gpu_left) - res.state.gpu_left).sum()
            )
            assert state_used == used, name


class TestBellmanSeries:
    def test_incremental_matches_direct_sweep(self):
        """_bellman_series's host-side state reconstruction + one-node
        updates must equal a direct node_frag_bellman sweep over the true
        post-event states."""
        from tpusim.ops.frag import node_frag_bellman
        from tpusim.sim.engine import EV_CREATE, EV_DELETE

        nodes = [
            NodeRow("n0", 16000, 65536, 2, "V100M16"),
            NodeRow("n1", 32000, 65536, 4, "V100M16"),
        ]
        pods = [
            PodRow("a", 2000, 1024, 1, 500),
            PodRow("b", 4000, 1024, 1, 1000),
            PodRow("c", 1000, 1024, 1, 250),
        ]
        sim = Simulator(
            nodes,
            SimulatorConfig(
                policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore"
            ),
        )
        sim.set_workload_pods(pods)
        sim.set_typical_pods()
        import jax

        from tpusim.io.trace import pods_to_specs

        specs = pods_to_specs(pods)
        ev_kind = jnp.asarray([EV_CREATE, EV_CREATE, EV_DELETE, EV_CREATE], jnp.int32)
        ev_pod = jnp.asarray([0, 1, 0, 2], jnp.int32)
        out = sim.run_events(sim.init_state, specs, ev_kind, ev_pod, jax.random.PRNGKey(0))
        series = sim._bellman_series(sim.init_state, pods, ev_kind, ev_pod, out)

        # direct sweep: replay states host-side and evaluate every node
        t = sim.typical
        typ = list(zip(
            np.asarray(t.cpu).tolist(), np.asarray(t.gpu_milli).tolist(),
            np.asarray(t.gpu_num).tolist(), np.asarray(t.gpu_mask).tolist(),
            np.asarray(t.freq).tolist(),
        ))
        cpu = np.asarray(sim.init_state.cpu_left).copy()
        gpu = np.asarray(sim.init_state.gpu_left).copy()
        gt = np.asarray(sim.init_state.gpu_type)
        ev_node = np.asarray(out.event_node)
        ev_dev = np.asarray(out.event_dev)
        for e in range(len(ev_kind)):
            n = int(ev_node[e])
            if n >= 0:
                p = pods[int(ev_pod[e])]
                sign = 1 if int(ev_kind[e]) == EV_CREATE else -1
                cpu[n] -= sign * p.cpu_milli
                gpu[n][ev_dev[e]] -= sign * p.gpu_milli
            direct = sum(
                node_frag_bellman(
                    (int(cpu[i]), tuple(int(g) for g in gpu[i]), int(gt[i])), typ
                )
                for i in range(len(nodes))
            )
            assert abs(direct - series[e]) < 1e-6, e
        # and the reconstruction matches the device end state exactly
        np.testing.assert_array_equal(cpu, np.asarray(out.state.cpu_left))
        np.testing.assert_array_equal(gpu, np.asarray(out.state.gpu_left))


class TestTimestampReplay:
    """Annotation-driven create+delete replay (ref: simulator.go:672-717):
    event expansion, stable timestamp sort, and end-to-end resource reuse
    after deletions."""

    def test_build_events_expansion_and_stable_sort(self):
        from tpusim.io.trace import build_events
        from tpusim.sim.engine import EV_SKIP

        pods = [
            PodRow("a", 1000, 0, 0, 0, creation_time=5, deletion_time=10),
            PodRow("b", 1000, 0, 0, 0, creation_time=5),  # tie with a: stable
            PodRow("c", 1000, 0, 0, 0, creation_time=0),  # zero sorts first
            PodRow("d", 1000, 0, 0, 0, creation_time=7, deletion_time=8),
            PodRow("e", 1000, 0, 0, 0, creation_time=6, unscheduled=True,
                   deletion_time=9),
        ]
        kind, idx = build_events(pods, use_timestamps=True)
        # timeline: c@0, a@5, b@5 (stable: a appended first), e@6 (skip,
        # no deletion event for an unscheduled pod — the reference skips
        # both its events at processing, simulator.go:391-399), d@7,
        # d-delete@8, a-delete@10
        assert [int(k) for k in kind] == [
            EV_CREATE, EV_CREATE, EV_CREATE, EV_SKIP, EV_CREATE,
            EV_DELETE, EV_DELETE,
        ]
        assert [int(i) for i in idx] == [2, 0, 1, 4, 3, 3, 0]

    def test_build_events_no_deletion_without_timestamp(self):
        from tpusim.io.trace import build_events

        pods = [PodRow("a", 1000, 0, 0, 0, creation_time=3)]
        kind, idx = build_events(pods, use_timestamps=True)
        assert len(kind) == 1 and int(kind[0]) == EV_CREATE

    def test_timestamp_replay_frees_resources(self):
        """A full-GPU pod deleted mid-stream must make room for a later
        arrival that would otherwise be unschedulable."""
        nodes = [NodeRow("n0", 16000, 65536, 1, "V100M16")]
        pods = [
            PodRow("first", 1000, 1024, 1, 1000, creation_time=1,
                   deletion_time=5),
            PodRow("second", 1000, 1024, 1, 1000, creation_time=9),
        ]
        cfg = SimulatorConfig(
            policies=(("BestFitScore", 1000),), use_timestamps=True
        )
        sim = Simulator(nodes, cfg)
        sim.set_workload_pods(pods)
        res = sim.run()
        assert not res.unscheduled_pods
        assert res.events == 3  # create, delete, create
        # "first" was deleted (placed_node reflects final placement state)
        assert res.placed_node[0] == -1 and res.placed_node[1] == 0
        assert int(np.asarray(res.state.gpu_left).sum()) == 0  # second holds it

        # without the knob the same workload cannot fit both pods
        sim2 = Simulator(nodes, SimulatorConfig(policies=(("BestFitScore", 1000),)))
        sim2.set_workload_pods(pods)
        res2 = sim2.run()
        assert len(res2.unscheduled_pods) == 1

    def test_simon_cr_knob_reaches_simulator_config(self, tmp_path):
        from tpusim.config.simon import parse_simon_cr

        doc = {
            "apiVersion": "simon/v1alpha1",
            "kind": "Config",
            "spec": {
                "cluster": {"customConfig": str(tmp_path)},
                "customConfig": {"useTimestamps": True},
            },
        }
        cr = parse_simon_cr(doc)
        assert cr.custom_config.use_timestamps is True
        assert parse_simon_cr(
            {**doc, "spec": {**doc["spec"], "customConfig": {}}}
        ).custom_config.use_timestamps is False
