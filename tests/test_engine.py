"""Scheduler-step and replay-engine tests: filter semantics, bind/unbind
accounting, event loop, and a small end-to-end driver run."""

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.constants import GPU_MODEL_IDS, MILLI
from tpusim.io.trace import NodeRow, PodRow
from tpusim.policies import make_policy
from tpusim.sim.driver import Simulator, SimulatorConfig
from tpusim.sim.engine import EV_CREATE, EV_DELETE, make_replay
from tpusim.sim.step import filter_nodes, schedule_one
from tpusim.types import make_node_state, make_pod, make_typical_pods


def two_nodes():
    return make_node_state(
        cpu_cap=[32000, 96000],
        mem_cap=[262144, 786432],
        gpu_cnt=[0, 4],
        gpu_type=[-1, GPU_MODEL_IDS["V100M16"]],
    )


TP = make_typical_pods([(1000, 500, 1, 0, 1.0)])


class TestFilter:
    def test_gpu_pod_rejects_cpu_node(self):
        st = two_nodes()
        pod = make_pod(cpu=1000, gpu_milli=500, gpu_num=1)
        np.testing.assert_array_equal(
            np.asarray(filter_nodes(st, pod)), [False, True]
        )

    def test_model_constraint(self):
        st = two_nodes()
        mask_a100 = 1 << GPU_MODEL_IDS["A100"]
        pod = make_pod(cpu=1000, gpu_milli=500, gpu_num=1, gpu_mask=mask_a100)
        assert not bool(filter_nodes(st, pod)[1])
        mask_v100 = 1 << GPU_MODEL_IDS["V100M16"]
        pod2 = make_pod(cpu=1000, gpu_milli=500, gpu_num=1, gpu_mask=mask_v100)
        assert bool(filter_nodes(st, pod2)[1])

    def test_cpu_fit(self):
        st = two_nodes()
        pod = make_pod(cpu=50000)
        np.testing.assert_array_equal(
            np.asarray(filter_nodes(st, pod)), [False, True]
        )

    def test_multi_gpu_fit(self):
        st = two_nodes()
        pod = make_pod(cpu=100, gpu_milli=1000, gpu_num=5)
        assert not bool(filter_nodes(st, pod)[1])
        pod4 = make_pod(cpu=100, gpu_milli=1000, gpu_num=4)
        assert bool(filter_nodes(st, pod4)[1])


class TestScheduleOne:
    def test_bind_updates_state(self):
        st = two_nodes()
        pod = make_pod(cpu=2000, mem=1024, gpu_milli=500, gpu_num=1)
        pols = [(make_policy("BestFitScore"), 1000)]
        new, pl = schedule_one(st, pod, jax.random.PRNGKey(0), pols, "best", TP)
        assert int(pl.node) == 1
        assert int(new.cpu_left[1]) == 96000 - 2000
        assert int(new.mem_left[1]) == 786432 - 1024
        assert int(np.asarray(new.gpu_left[1]).sum()) == 4000 - 500
        assert int(np.asarray(pl.dev_mask).sum()) == 1
        assert int(new.aff_cnt[1, 0]) == 1  # share class

    def test_unschedulable(self):
        st = two_nodes()
        pod = make_pod(cpu=100, gpu_milli=1000, gpu_num=8)
        pols = [(make_policy("BestFitScore"), 1000)]
        new, pl = schedule_one(st, pod, jax.random.PRNGKey(0), pols, "best", TP)
        assert int(pl.node) == -1
        np.testing.assert_array_equal(
            np.asarray(new.cpu_left), np.asarray(st.cpu_left)
        )
        np.testing.assert_array_equal(
            np.asarray(new.gpu_left), np.asarray(st.gpu_left)
        )

    def test_share_gpu_best_fit_device(self):
        st = two_nodes()
        st = st._replace(gpu_left=st.gpu_left.at[1, 0].set(600))
        pod = make_pod(cpu=100, gpu_milli=500, gpu_num=1)
        pols = [(make_policy("BestFitScore"), 1000)]
        new, pl = schedule_one(st, pod, jax.random.PRNGKey(0), pols, "best", TP)
        # tightest fitting device is d0 (600m left)
        assert bool(pl.dev_mask[0]) and int(np.asarray(pl.dev_mask).sum()) == 1
        assert int(new.gpu_left[1, 0]) == 100


class TestReplay:
    def test_create_then_delete_restores_state(self):
        st = two_nodes()
        pods = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            make_pod(cpu=2000, gpu_milli=500, gpu_num=1),
            make_pod(cpu=1000, gpu_milli=1000, gpu_num=2),
        )
        replay = make_replay([(make_policy("FGDScore"), 1000)], "FGDScore")
        ev_kind = jnp.asarray([EV_CREATE, EV_CREATE, EV_DELETE, EV_DELETE], jnp.int32)
        ev_pod = jnp.asarray([0, 1, 0, 1], jnp.int32)
        res = replay(st, pods, ev_kind, ev_pod, TP, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(
            np.asarray(res.state.cpu_left), np.asarray(st.cpu_left)
        )
        np.testing.assert_array_equal(
            np.asarray(res.state.gpu_left), np.asarray(st.gpu_left)
        )
        np.testing.assert_array_equal(
            np.asarray(res.state.aff_cnt), np.asarray(st.aff_cnt)
        )
        assert int(res.placed_node[0]) == -1  # deleted again
        # metrics rows exist for every event
        assert res.metrics.frag_amounts.shape == (4, 7)
        # arrived counters only accumulate on creations
        assert int(res.metrics.arrived_gpu_milli[-1]) == 500 + 2000
        assert int(res.metrics.arrived_cpu_milli[-1]) == 3000

    def test_failed_pod_leaves_no_trace(self):
        st = two_nodes()
        pods = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            make_pod(cpu=100, gpu_milli=1000, gpu_num=8),
        )
        replay = make_replay([(make_policy("BestFitScore"), 1000)], "best")
        res = replay(
            st, pods, jnp.asarray([EV_CREATE], jnp.int32),
            jnp.asarray([0], jnp.int32), TP, jax.random.PRNGKey(0),
        )
        assert bool(res.ever_failed[0])
        assert int(res.placed_node[0]) == -1
        np.testing.assert_array_equal(
            np.asarray(res.state.gpu_left), np.asarray(st.gpu_left)
        )


class TestDriverEndToEnd:
    def nodes(self):
        return [
            NodeRow("n-cpu", 32000, 262144, 0, ""),
            NodeRow("n-v100", 96000, 786432, 8, "V100M16"),
            NodeRow("n-a100", 96000, 786432, 4, "A100"),
        ]

    def pods(self):
        rows = []
        for i in range(4):
            rows.append(PodRow(f"p-share-{i}", 4000, 8192, 1, 500, "", creation_time=i))
        rows.append(PodRow("p-multi", 8000, 16384, 2, 1000, "", creation_time=10))
        rows.append(PodRow("p-a100", 4000, 8192, 1, 1000, "A100", creation_time=11))
        rows.append(PodRow("p-cpu", 2000, 4096, 0, 0, "", creation_time=12))
        return rows

    def test_fgd_run(self):
        sim = Simulator(self.nodes(), SimulatorConfig(policies=(("FGDScore", 1000),),
                                                      gpu_sel_method="FGDScore"))
        sim.set_workload_pods(self.pods())
        res = sim.run()
        assert not res.unscheduled_pods
        # A100-constrained pod must land on the A100 node (index 2)
        assert res.placed_node[5] == 2
        # placements conserve resources
        total_gpu_used = sum(
            p.total_gpu_milli for p, n in zip(res.pods, res.placed_node) if n >= 0
        )
        state_used = int(
            (np.asarray(sim.init_state.gpu_left) - res.state.gpu_left).sum()
        )
        assert state_used == total_gpu_used
        # log contract: per-event lines + 16-line analysis block present
        sim.finish()
        text = sim.log.dump()
        # one [Report] block per create/delete event (skip events emit none,
        # simulator.go:391-399; this workload has no skips)
        assert text.count("[Report]") == res.events
        assert "Cluster Analysis Results (InitSchedule)" in text
        assert "there are 0 unscheduled pods" in text

    def test_policy_sweep_all_run(self):
        for name in (
            "BestFitScore", "GpuPackingScore", "GpuClusteringScore",
            "RandomScore", "DotProductScore", "PWRScore", "Simon",
        ):
            gpu_sel = name if name in ("DotProductScore", "PWRScore") else "best"
            sim = Simulator(
                self.nodes(),
                SimulatorConfig(policies=((name, 1000),), gpu_sel_method=gpu_sel,
                                report_per_event=False),
            )
            sim.set_workload_pods(self.pods())
            res = sim.run()
            # policies may legitimately strand the A100-constrained pod by
            # filling the A100 node first; anything else must place
            assert all(
                u.pod.name == "p-a100" for u in res.unscheduled_pods
            ), name
            # placements conserve GPU milli
            used = sum(
                p.total_gpu_milli
                for p, n in zip(res.pods, res.placed_node)
                if n >= 0
            )
            state_used = int(
                (np.asarray(sim.init_state.gpu_left) - res.state.gpu_left).sum()
            )
            assert state_used == used, name
