"""On-TPU test lane: `TPUSIM_TPU_TESTS=1 pytest -m tpu`.

Asserts that the accelerator backend reproduces the CPU/Go-oracle
numerics: the golden frag values from the reference's frag_test.go, and
sequential-engine vs incremental-table-engine placement equality — the
same invariants the CPU suite pins, re-checked on real TPU hardware
(VERDICT round 1: "No test runs on the TPU backend").
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.tpu


@pytest.fixture(scope="module")
def accel():
    dev = jax.devices()[0]
    if dev.platform == "cpu":
        pytest.skip("no accelerator backend available")
    return dev


def test_backend_is_accelerator(accel):
    assert accel.platform != "cpu"


def test_golden_frag_values_on_tpu(accel):
    """frag_test.go golden values, computed with TPU numerics (same shared
    cases the CPU suite pins — tests/fixtures.py FRAG_SCORE_GOLDENS)."""
    from tests.fixtures import FRAG_SCORE_GOLDENS, frag_golden_score

    for case in FRAG_SCORE_GOLDENS:
        actual, expected = frag_golden_score(case)
        assert actual == pytest.approx(expected, abs=0.05), case


def test_cluster_frag_report_tpu_matches_cpu(accel):
    """The vmapped cluster report must agree between TPU and host-CPU
    backends on a heterogeneous random cluster (f32 sums: exactness up to
    reduction order; assert tight tolerance)."""
    from tests.fixtures import random_cluster
    from tpusim.ops.frag import cluster_frag_report

    rng = np.random.default_rng(11)
    state, tp = random_cluster(rng, num_nodes=64)
    amounts_tpu = np.asarray(cluster_frag_report(state, tp)[0])

    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        state_c = jax.device_put(state, cpu)
        tp_c = jax.device_put(tp, cpu)
        amounts_cpu = np.asarray(cluster_frag_report(state_c, tp_c)[0])
    np.testing.assert_allclose(amounts_tpu, amounts_cpu, rtol=1e-6, atol=0.5)


def test_engine_vs_table_engine_on_tpu(accel):
    """Placement-for-placement equality of the two engines, on device
    (the CPU suite pins this per policy; one FGD mix suffices on-chip)."""
    from tests.fixtures import random_cluster, random_pods
    from tpusim.policies import make_policy
    from tpusim.sim.engine import EV_CREATE, make_replay
    from tpusim.sim.table_engine import build_pod_types, make_table_replay

    rng = np.random.default_rng(5)
    state, tp = random_cluster(rng, num_nodes=32)
    pods = random_pods(rng, num_pods=48)
    ev_kind = jnp.full(48, EV_CREATE, jnp.int32)
    ev_pod = jnp.arange(48, dtype=jnp.int32)
    policies = [(make_policy("FGDScore"), 1000)]
    key = jax.random.PRNGKey(2)
    rank = jnp.asarray(rng.permutation(32).astype(np.int32))

    seq = make_replay(policies, "FGDScore", report=False)(
        state, pods, ev_kind, ev_pod, tp, key, rank
    )
    types = build_pod_types(pods)
    tab = make_table_replay(policies, "FGDScore", report=False)(
        state, pods, types, ev_kind, ev_pod, tp, key, rank
    )
    assert np.array_equal(np.asarray(seq.placed_node), np.asarray(tab.placed_node))
    assert np.array_equal(np.asarray(seq.dev_mask), np.asarray(tab.dev_mask))
    for a, b in zip(jax.tree.leaves(seq.state), jax.tree.leaves(tab.state)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize(
    "policy,gpu_sel",
    [("FGDScore", "FGDScore"), ("PWRScore", "PWRScore")],
    ids=["fgd", "pwr"],
)
def test_pallas_engine_full_openb_on_tpu(accel, policy, gpu_sel):
    """The fused whole-replay Pallas kernel must reproduce the table
    engine's placements/devices/state bit-for-bit on the FULL openb default
    trace at tune 1.3 — the headline-bench configuration. This is the
    pallas engine's exactness gate on real Mosaic numerics (the CPU suite
    only covers interpreter mode). FGD covers the frag f32 sums; PWR covers
    the energy-table lookups and its own normalize mode."""
    import os

    from tpusim.io.trace import build_events, load_node_csv, load_pod_csv, pods_to_specs
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.pallas_engine import make_pallas_replay
    from tpusim.sim.table_engine import build_pod_types
    from tpusim.sim.typical import TypicalPodsConfig

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    nodes = load_node_csv(os.path.join(repo, "data/csv/openb_node_list_gpu_node.csv"))
    pods = load_pod_csv(os.path.join(repo, "data/csv/openb_pod_list_default.csv"))
    cfg = SimulatorConfig(
        policies=((policy, 1000),), gpu_sel_method=gpu_sel,
        tuning_ratio=1.3, tuning_seed=42, seed=42, shuffle_pod=True,
        report_per_event=False,
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
    )
    sim = Simulator(nodes, cfg)
    sim.set_workload_pods(pods)
    sim.set_typical_pods()
    trace = sim.prepare_pods()
    specs = pods_to_specs(trace)
    ev_kind, ev_pod = build_events(trace)
    ev_kind, ev_pod = jnp.asarray(ev_kind), jnp.asarray(ev_pod)
    key = jax.random.PRNGKey(42)
    types = build_pod_types(specs)

    tab = sim._table_fn(
        sim.init_state, specs, types, ev_kind, ev_pod, sim.typical, key, sim.rank
    )
    pal = make_pallas_replay(list(sim._policy_fns), gpu_sel=gpu_sel)(
        sim.init_state, specs, types, ev_kind, ev_pod, sim.typical, key, sim.rank
    )
    assert np.array_equal(np.asarray(tab.placed_node), np.asarray(pal.placed_node))
    assert np.array_equal(np.asarray(tab.dev_mask), np.asarray(pal.dev_mask))
    assert np.array_equal(np.asarray(tab.ever_failed), np.asarray(pal.ever_failed))
    assert np.array_equal(np.asarray(tab.event_node), np.asarray(pal.event_node))
    for a, b in zip(jax.tree.leaves(tab.state), jax.tree.leaves(pal.state)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_driver_small_run_on_tpu(accel):
    """A tiny end-to-end driver run on the accelerator: placements land,
    reports emit, no unscheduled pods."""
    from tpusim.io.trace import NodeRow, PodRow
    from tpusim.sim.driver import Simulator, SimulatorConfig

    nodes = [
        NodeRow("t-cpu", 32000, 262144, 0, ""),
        NodeRow("t-gpu", 96000, 786432, 8, "V100M16"),
    ]
    pods = [
        PodRow(f"p{i}", 4000, 8192, 1, 500, "", creation_time=i) for i in range(4)
    ] + [PodRow("pc", 2000, 4096, 0, 0, "", creation_time=9)]
    sim = Simulator(
        nodes,
        SimulatorConfig(policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore"),
    )
    sim.set_workload_pods(pods)
    res = sim.run()
    assert not res.unscheduled_pods
    assert (np.asarray(res.placed_node[:4]) == 1).all()
    assert "Cluster Analysis Results" in sim.log.dump()


def test_shardmap_engine_compiles_on_tpu(accel):
    """The explicit-collective shard_map engine must compile and run its
    collective path (psum/pmax lanes) on the real chip — the CPU suite only
    exercises it on the virtual host mesh (VERDICT r3 §6: 'the TPU test
    lane never compiles the collective path on real hardware'). One device
    suffices: the collectives still lower and execute, just degenerate."""
    from tests.fixtures import random_cluster, random_pods
    from tpusim.parallel.shard_engine import make_shardmap_table_replay
    from tpusim.parallel.sharding import make_mesh, pad_nodes, shard_state
    from tpusim.policies import make_policy
    from tpusim.sim.engine import EV_CREATE
    from tpusim.sim.table_engine import build_pod_types, make_table_replay

    rng = np.random.default_rng(17)
    state, tp = random_cluster(rng, num_nodes=24)
    pods = random_pods(rng, num_pods=40)
    ev_kind = jnp.full(40, EV_CREATE, jnp.int32)
    ev_pod = jnp.arange(40, dtype=jnp.int32)
    policies = [(make_policy("FGDScore"), 1000)]
    key = jax.random.PRNGKey(3)
    rank = jnp.asarray(rng.permutation(24).astype(np.int32))

    plain = make_table_replay(policies, "FGDScore", report=False)(
        state, pods, build_pod_types(pods), ev_kind, ev_pod, tp, key, rank
    )
    mesh = make_mesh(1)
    pstate, prank = pad_nodes(state, rank, 1)
    pstate = shard_state(pstate, mesh)
    sharded = make_shardmap_table_replay(policies, mesh, gpu_sel="FGDScore")(
        pstate, pods, build_pod_types(pods), ev_kind, ev_pod, tp, key, prank
    )
    assert np.array_equal(
        np.asarray(plain.placed_node), np.asarray(sharded.placed_node)
    )
    assert np.array_equal(
        np.asarray(plain.dev_mask), np.asarray(sharded.dev_mask)
    )
    for a, b in zip(jax.tree.leaves(plain.state), jax.tree.leaves(sharded.state)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_seed_batched_replay_on_tpu(accel):
    """Per-seed bit-identity of the vmapped batch on the real chip (the
    device where the sweep actually runs it)."""
    from tests.fixtures import random_cluster, random_pods
    from tpusim.io.trace import tiebreak_rank
    from tpusim.policies import make_policy
    from tpusim.sim.engine import EV_CREATE
    from tpusim.sim.table_engine import build_pod_types, make_table_replay

    rng = np.random.default_rng(23)
    state, tp = random_cluster(rng, num_nodes=24)
    pods = random_pods(rng, num_pods=40)
    ev_kind = jnp.full(40, EV_CREATE, jnp.int32)
    ev_pod = jnp.arange(40, dtype=jnp.int32)
    policies = [(make_policy("FGDScore"), 1000)]
    tab = make_table_replay(policies, "FGDScore", report=False)

    ranks = jnp.stack(
        [jnp.asarray(tiebreak_rank(24, s)) for s in range(4)]
    )
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(4))
    types = build_pod_types(pods)
    batched = jax.jit(
        jax.vmap(lambda k, r: tab(state, pods, types, ev_kind, ev_pod, tp, k, r))
    )(keys, ranks)
    for s in range(4):
        single = tab(state, pods, types, ev_kind, ev_pod, tp, keys[s], ranks[s])
        assert np.array_equal(
            np.asarray(single.placed_node), np.asarray(batched.placed_node[s])
        ), f"seed {s}"
