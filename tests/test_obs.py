"""tpusim.obs — telemetry, profiling, and the bench gate (ISSUE 3).

The contracts under test:
  (1) the in-scan counters are EXACT and engine-invariant — the same
      trace yields bit-identical counter vectors (modulo the documented
      engine-specific `rebuilds` slot) on the flat, blocked, sequential,
      and shard_map engines;
  (2) telemetry is continuous across checkpoint kill/resume and across
      fault-path segment splits — the resumed/segmented run's counters
      equal the uninterrupted run's;
  (3) the JSONL record's `deterministic` block is bit-identical across
      two same-seed runs;
  (4) the emitters round-trip their schema;
  (5) the content-keyed init_tables cache is bit-transparent;
  (6) the bench gate's parse/compare logic.
"""

import json
import os
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import random_cluster, random_pods
from tpusim.io.trace import NodeRow, PodRow, pods_to_specs
from tpusim.policies import make_policy
from tpusim.sim.driver import Simulator, SimulatorConfig
from tpusim.sim.engine import EV_CREATE, EV_DELETE, make_replay
from tpusim.sim.table_engine import build_pod_types, make_table_replay


def _mixed_events(num_pods, rng):
    kinds, idxs, seen = [], [], set()
    for i in range(num_pods):
        kinds.append(EV_CREATE)
        idxs.append(i)
        if rng.random() < 0.3 and i > 0:
            victim = int(rng.integers(0, i + 1))
            if victim not in seen:
                seen.add(victim)
                kinds.append(EV_DELETE)
                idxs.append(victim)
    return jnp.asarray(kinds, jnp.int32), jnp.asarray(idxs, jnp.int32)


@pytest.mark.slow
def test_counters_engine_invariant():
    """The same create/delete mix yields bit-identical invariant counters
    (creates/binds/fail_creates/deletes/skips) on the flat, blocked,
    sequential, and shard_map engines — and the counts agree with the
    per-event telemetry they summarize.

    slow-marked (tier-1 budget, ROADMAP): it compiles four engines; the
    tier-1 lane still pins table-engine counters through the driver tests
    below, and this runs under `make resume-smoke` / plain pytest."""
    from tpusim.obs.counters import counters_from_telemetry
    from tpusim.parallel import make_mesh, pad_nodes, shard_state
    from tpusim.parallel.shard_engine import make_shardmap_table_replay

    rng = np.random.default_rng(7)
    state, tp = random_cluster(rng, num_nodes=24)
    pods = random_pods(rng, num_pods=40)
    ev_kind, ev_pod = _mixed_events(40, rng)
    policies = [(make_policy("FGDScore"), 1000)]
    key = jax.random.PRNGKey(3)
    rank = jnp.asarray(rng.permutation(24).astype(np.int32))
    types = build_pod_types(pods)

    flat = make_table_replay(policies, gpu_sel="FGDScore", block_size=-1)(
        state, pods, types, ev_kind, ev_pod, tp, key, rank
    )
    blocked = make_table_replay(policies, gpu_sel="FGDScore", block_size=8)(
        state, pods, types, ev_kind, ev_pod, tp, key, rank
    )
    seq = make_replay(policies, gpu_sel="FGDScore", report=False)(
        state, pods, ev_kind, ev_pod, tp, key, rank
    )
    mesh = make_mesh(4)
    st_p, rank_p = pad_nodes(state, rank, 4)
    shard = make_shardmap_table_replay(policies, mesh, gpu_sel="FGDScore")(
        shard_state(st_p, mesh), pods, types, ev_kind, ev_pod, tp, key,
        rank_p,
    )

    ref = np.asarray(flat.counters)
    for out in (blocked, seq, shard):
        assert np.array_equal(np.asarray(out.counters)[:5], ref[:5])
        assert np.array_equal(
            np.asarray(out.placed_node), np.asarray(flat.placed_node)
        )
    # counters agree with the telemetry they summarize
    derived = counters_from_telemetry(ev_kind, flat.event_node)
    assert np.array_equal(derived[:5], ref[:5].astype(np.int64))
    # sanity: the mix actually exercised creates AND deletes
    assert ref[0] > 0 and ref[3] > 0 and ref[0] == ref[1] + ref[2]


def _driver_inputs():
    rng = np.random.default_rng(31)
    nodes = [
        NodeRow(f"n{i}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], 12))
    ]
    pods = [
        PodRow(f"p{i}", int(rng.choice([1000, 4000])), 1024,
               int(rng.choice([0, 1])), 500)
        for i in range(30)
    ]
    return nodes, pods


def _run_driver(nodes, pods, every=0, ckdir="", seed=42, profile=False,
                table_cache=""):
    sim = Simulator(nodes, SimulatorConfig(
        policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
        report_per_event=True, checkpoint_every=every,
        checkpoint_dir=ckdir, seed=seed, profile=profile,
        table_cache_dir=table_cache,
    ))
    sim.set_workload_pods(pods)
    sim.set_typical_pods()
    specs = pods_to_specs(pods)
    out = sim.run_events(
        sim.init_state, specs, jnp.zeros(len(pods), jnp.int32),
        jnp.arange(len(pods), dtype=jnp.int32), jax.random.PRNGKey(2),
    )
    return sim, out


@pytest.mark.slow  # tier-1 trim, ISSUE 16: rides resume-smoke
def test_counters_survive_kill_resume(tmp_path):
    """Telemetry continuity across checkpoint kill/resume: the counters
    ride the carry, so a resumed run's final vector is bit-identical to
    the uninterrupted run's (nothing is double- or under-counted)."""
    import tpusim.io.storage as storage

    nodes, pods = _driver_inputs()
    _, r0 = _run_driver(nodes, pods)
    assert r0.counters is not None

    real_save = storage.save_checkpoint

    def killing_save(*a, **k):
        real_save(*a, **k)
        raise KeyboardInterrupt("simulated preemption")

    storage.save_checkpoint = killing_save
    try:
        with pytest.raises(KeyboardInterrupt):
            _run_driver(nodes, pods, every=10, ckdir=str(tmp_path))
    finally:
        storage.save_checkpoint = real_save
    assert os.listdir(tmp_path)

    sim, r2 = _run_driver(nodes, pods, every=10, ckdir=str(tmp_path))
    assert any("[Checkpoint] resumed replay" in l for l in sim.log.lines)
    assert np.array_equal(np.asarray(r0.counters), np.asarray(r2.counters))
    # and through the telemetry record (padding-corrected dict form)
    rec = sim.run_telemetry().to_record()
    assert rec["deterministic"]["counters"]["creates"] == len(pods)
    assert rec["deterministic"]["counters"]["skips"] == 0  # padding removed


def test_telemetry_record_deterministic_and_profiled():
    """Two same-seed profiled runs emit bit-identical `deterministic`
    blocks; profiling attributes walls to the compile(dispatch)/execute
    (block) halves of the scan span."""
    nodes, pods = _driver_inputs()
    sim1, _ = _run_driver(nodes, pods, profile=True)
    sim2, _ = _run_driver(nodes, pods, profile=True)
    rec1 = sim1.run_telemetry().to_record()
    rec2 = sim2.run_telemetry().to_record()
    blob1 = json.dumps(rec1["deterministic"], sort_keys=True)
    blob2 = json.dumps(rec2["deterministic"], sort_keys=True)
    assert blob1 == blob2
    names = [s["name"] for s in rec1["timing"]["spans"]]
    assert "scan" in names and "typical_pods" in names
    scan = next(s for s in rec1["timing"]["spans"] if s["name"] == "scan")
    assert scan["dispatch_s"] >= 0 and scan["block_s"] >= 0
    # the three fields are rounded to 6 dp independently
    assert scan["total_s"] == pytest.approx(
        scan["dispatch_s"] + scan["block_s"], abs=2e-6
    )
    assert rec1["deterministic"]["engines"] == ["table"]


def test_fault_run_counters_and_disruption():
    """The fault path's segmented replays accumulate into ONE counter set
    (continuity across segments), and the [Disruption] block's totals are
    machine-readable from the record — same numbers, same seed, twice."""
    from tpusim.sim.engine import EV_NODE_FAIL
    from tpusim.sim.faults import FaultEvent

    nodes, pods = _driver_inputs()
    faults = [FaultEvent(pos=10, kind=EV_NODE_FAIL, node=0)]

    def run():
        sim = Simulator(nodes, SimulatorConfig(
            policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
            report_per_event=False, seed=42,
        ))
        sim.set_workload_pods(pods)
        res = sim.schedule_pods_with_faults(pods, faults=faults)
        return sim, res

    sim1, res1 = run()
    sim2, res2 = run()
    rec1 = res1.telemetry.to_record()["deterministic"]
    rec2 = res2.telemetry.to_record()["deterministic"]
    assert rec1 == rec2
    dm = sim1.last_disruption
    assert rec1["disruption"]["node_failures"] == dm.node_failures == 1
    assert rec1["disruption"]["evicted_pods"] == dm.evicted_pods
    # creates across ALL segments = base creations + retry re-creations
    assert rec1["counters"]["creates"] == len(pods) + dm.retries_enqueued
    assert rec1["counters"]["skips"] == 0


def test_emitter_schema_roundtrip(tmp_path):
    """JSONL append/read round-trip, Prometheus textfile well-formedness,
    Chrome-trace structure — on a real recorder snapshot."""
    from tpusim.obs import Recorder, emitters

    rec = Recorder(enabled=True)
    with rec.span("scan", engine="table") as h:
        h.dispatched()
    rec.count("degrade_vmem")
    rec.note_scan("table", counters=np.array([5, 4, 1, 0, 2, 0]),
                  pad_skips=2, events=5)
    tel = rec.snapshot(meta={"seed": 1})
    record = tel.to_record()
    assert record["schema"] == "tpusim-obs-v1"
    assert record["deterministic"]["counters"] == {
        "creates": 5, "binds": 4, "fail_creates": 1, "deletes": 0,
        "skips": 0, "rebuilds": 0,
    }
    assert record["deterministic"]["degrades"] == {"degrade_vmem": 1}

    # JSONL: append twice, read back both, bit-identical lines
    path = str(tmp_path / "runs.jsonl")
    emitters.append_jsonl(path, record)
    emitters.append_jsonl(path, record)
    lines = open(path).read().splitlines()
    assert len(lines) == 2 and lines[0] == lines[1]
    assert emitters.read_jsonl(path)[0] == record

    # Prometheus: every line is a comment or `name{labels} value`
    prom = str(tmp_path / "m.prom")
    emitters.write_prometheus(prom, record)
    sample = re.compile(
        r"^[a-z0-9_]+(\{[^}]*\})? -?[0-9.e+-]+$"
    )
    for line in open(prom).read().splitlines():
        assert line.startswith("# TYPE ") or sample.match(line), line
    assert "tpusim_counter_binds 4" in open(prom).read()

    # Chrome trace: a JSON object with X-phase events in microseconds
    tr = str(tmp_path / "t.json")
    emitters.write_chrome_trace(tr, tel.spans)
    data = json.loads(open(tr).read())
    assert data["traceEvents"], "no trace events"
    for ev in data["traceEvents"]:
        assert ev["ph"] == "X" and "ts" in ev and "dur" in ev


def test_table_cache_bit_transparent(tmp_path):
    """Content-keyed init_tables reuse: first run misses and persists,
    second (fresh Simulator, same inputs) hits — placements, counters,
    and metrics bit-identical; a config change changes the key."""
    nodes, pods = _driver_inputs()
    cache = str(tmp_path / "tables")
    _, r0 = _run_driver(nodes, pods)  # uncached reference
    sim1, r1 = _run_driver(nodes, pods, table_cache=cache)
    sim2, r2 = _run_driver(nodes, pods, table_cache=cache)
    assert sim1.obs.table_cache == "miss"
    assert sim2.obs.table_cache == "hit"
    assert any("[TableCache] reused" in l for l in sim2.log.lines)
    for r in (r1, r2):
        assert np.array_equal(
            np.asarray(r0.placed_node), np.asarray(r.placed_node)
        )
        assert np.array_equal(np.asarray(r0.counters), np.asarray(r.counters))
        for a, b in zip(jax.tree.leaves(r0.state), jax.tree.leaves(r.state)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
    assert len(os.listdir(cache)) == 1
    # different seed -> different tie-break rank but SAME tables digest
    # (the build never reads rank/key): still a hit, still exact
    sim3, _ = _run_driver(nodes, pods, seed=43, table_cache=cache)
    assert sim3.obs.table_cache == "hit"


@pytest.mark.slow
def test_heartbeat_ticks_from_scan():
    """A heartbeat-built table engine fires host ticks every N processed
    events without touching the trajectory. slow-marked: heartbeat_every
    is part of the engine cache key, so this test pays a full extra
    engine compile; runs under `make resume-smoke` / plain pytest."""
    from tpusim.obs import heartbeat

    rng = np.random.default_rng(7)
    state, tp = random_cluster(rng, num_nodes=24)
    pods = random_pods(rng, num_pods=40)
    ev_kind = jnp.zeros(40, jnp.int32)
    ev_pod = jnp.arange(40, dtype=jnp.int32)
    policies = [(make_policy("FGDScore"), 1000)]
    rank = jnp.arange(24, dtype=jnp.int32)
    types = build_pod_types(pods)
    key = jax.random.PRNGKey(3)

    ref = make_table_replay(policies, gpu_sel="FGDScore", block_size=-1)(
        state, pods, types, ev_kind, ev_pod, tp, key, rank
    )
    lines = []
    old_min = heartbeat.MIN_INTERVAL_S
    heartbeat.MIN_INTERVAL_S = 0.0
    try:
        heartbeat.configure(40, "test", sink=lines.append)
        hb = make_table_replay(
            policies, gpu_sel="FGDScore", block_size=-1, heartbeat_every=10
        )(state, pods, types, ev_kind, ev_pod, tp, key, rank)
        jax.block_until_ready(hb.state)
    finally:
        heartbeat.MIN_INTERVAL_S = old_min
    assert heartbeat.tick_count() == 4  # 10, 20, 30, 40
    assert all("events" in l for l in lines)
    assert np.array_equal(
        np.asarray(ref.placed_node), np.asarray(hb.placed_node)
    )


def test_heartbeat_tail_relative_resume():
    """The honest-progress satellite (ISSUE 16): a scan resumed from a
    checkpoint (or a fork restored from a base carry) reports rate and
    ETA over the events THIS process actually executed — note_resume's
    done0 never counts toward ev/s, and the fault path's `base` offset
    shifts the run-level done counter without inflating the rate."""
    from tpusim.obs import heartbeat

    infos = []
    heartbeat.add_listener(infos.append)
    try:
        heartbeat.configure(100, "test", sink=lambda _line: None)
        heartbeat.note_resume(90)
        t0 = heartbeat._STATE["t0"]
        heartbeat._STATE["t0"] = t0 - 2.0  # a deterministic 2s clock
        heartbeat.tick(95)
        info = infos[-1]
        assert info["done"] == 95 and info["total"] == 100
        # 5 fresh events over ~2s — never 95/2
        assert 2.0 <= info["rate"] <= 3.0
        assert info["eta"] == pytest.approx(5 / info["rate"], rel=0.05)

        # the fault-segment offset: device counts restart at 0, the
        # run-level done is base + raw, the rate is still fresh-only
        heartbeat.configure(100, "test", sink=lambda _line: None,
                            base=40)
        heartbeat._STATE["t0"] -= 2.0
        heartbeat.tick(10)
        info = infos[-1]
        assert info["done"] == 50 and 4.0 <= info["rate"] <= 6.0

        # complete() disarms with the same fresh-only mean
        heartbeat.complete()
        assert infos[-1]["final"] is True
        heartbeat.complete()  # second call is a no-op
    finally:
        heartbeat.remove_listener(infos.append)
        heartbeat._STATE["total"] = 0


def test_gate_parse_and_compare(tmp_path):
    """latest_baseline parses the committed BENCH_r*.json shape; compare
    fails on quality drift, tolerates same-backend throughput noise, and
    treats cross-backend throughput as advisory."""
    from tpusim.obs import gate

    payload = {
        "n": 7, "cmd": "python bench.py", "rc": 0,
        "tail": "WARNING: Platform 'axon' is experimental\n"
        "[bench] events=10811 placed=8350 wall=0.19s "
        "(first incl. compile 5.0s) gpu_alloc=95.52% \n",
        "parsed": {"metric": "m", "value": 43841.3,
                   "unit": "placements/sec"},
    }
    with open(tmp_path / "BENCH_r07.json", "w") as f:
        json.dump(payload, f)
    # an older, and a torn, baseline must lose to / not shadow r07
    with open(tmp_path / "BENCH_r06.json", "w") as f:
        json.dump({**payload, "n": 6, "parsed": {"value": 1.0}}, f)
    (tmp_path / "BENCH_r08.json").write_text("{not json")
    base = gate.latest_baseline(str(tmp_path))
    assert base["n"] == 7 and base["events"] == 10811
    assert base["placed"] == 8350 and base["backend"] == "axon"
    assert base["gpu_alloc"] == pytest.approx(95.52)

    cur = {"throughput": 100.0, "events": 10811, "placed": 8350,
           "gpu_alloc": 95.52, "backend": "cpu"}
    ok, msgs = gate.compare(base, cur, tol=0.5, alloc_tol=0.05)
    assert ok, msgs  # cross-backend throughput is advisory
    assert any("advisory" in m for m in msgs)

    bad = dict(cur, placed=8349)
    ok, _ = gate.compare(base, bad, tol=0.5, alloc_tol=0.05)
    assert not ok  # one lost placement fails the gate

    same_backend = dict(cur, backend="axon", throughput=43841.3 * 0.4)
    ok, _ = gate.compare(base, same_backend, tol=0.5, alloc_tol=0.05)
    assert not ok  # same-backend 60% regression fails


def test_prometheus_type_declared_once_per_metric(tmp_path):
    """Strict promtext parsers reject duplicate `# TYPE` declarations:
    two samples of one metric name — labeled span series, or two record
    keys sanitizing to the same name — must share ONE declaration."""
    from tpusim.obs import Recorder, emitters

    rec = Recorder(enabled=True)
    # two spans of the same name -> labeled samples under one metric
    for _ in range(2):
        with rec.span("scan", engine="table") as h:
            h.dispatched()
    # two count keys that sanitize to the SAME metric name
    rec.count("cache hit")
    rec.count("cache_hit", 2)
    record = rec.snapshot(meta={}).to_record()
    lines = emitters.prometheus_lines(record)
    types = [l.split()[2] for l in lines if l.startswith("# TYPE ")]
    assert len(types) == len(set(types)), types
    # ... and one SAMPLE per (name, labelset): the colliding count keys
    # collapse to a single line instead of an invalid duplicate pair
    samples = [l for l in lines if not l.startswith("#")]
    keys = [l.rsplit(" ", 1)[0] for l in samples]
    assert len(keys) == len(set(keys)), keys
    assert sum(k == "tpusim_count_cache_hit" for k in keys) == 1
    # the span series still carries both labeled samples
    span_samples = [
        l for l in lines if l.startswith("tpusim_span_seconds_total{")
    ]
    assert len(span_samples) >= 2


def test_heartbeat_final_tick():
    """complete() always emits one 100% line (total wall + mean ev/s)
    even when the run finished inside the rate limit, then disarms —
    repeated calls and unarmed calls are no-ops."""
    from tpusim.obs import heartbeat

    lines = []
    heartbeat.configure(40, "scan", sink=lines.append)
    # run finished before any periodic tick fired
    heartbeat.complete()
    assert len(lines) == 1
    assert "40/40" in lines[0] and "ev/s mean" in lines[0]
    assert heartbeat.tick_count() == 1
    heartbeat.complete()  # disarmed: no second line
    assert len(lines) == 1
    # armed with a bucket-PADDED size, completed with the true count:
    # the final line reports the pre-padding total
    heartbeat.configure(512, "scan", sink=lines.append)
    heartbeat.complete(40)
    assert len(lines) == 2 and "40/40" in lines[1]


@pytest.mark.slow
def test_heartbeat_final_tick_from_driver(monkeypatch):
    """A heartbeat-configured driver replay always fires complete() with
    the heartbeat still armed — i.e. a run too short for any periodic
    tick (rate limit / large `every`) still reports its final line.
    slow-marked (tier-1 budget): heartbeat_every is part of the engine
    cache key, so this pays a fresh engine compile; the complete() host
    logic itself is tier-1-covered by test_heartbeat_final_tick."""
    from tpusim.obs import heartbeat

    calls = []
    real_complete = heartbeat.complete

    def spy(true_total=0):
        calls.append(heartbeat._STATE["total"])  # armed total at fire time
        calls.append(true_total)  # the driver's PRE-padding event count
        lines = []
        heartbeat._STATE["sink"] = lines.append
        real_complete(true_total)
        calls.append(lines[0] if lines else None)

    monkeypatch.setattr(heartbeat, "complete", spy)
    nodes, pods = _driver_inputs()
    sim = Simulator(nodes, SimulatorConfig(
        policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
        report_per_event=False, heartbeat_every=10_000,
    ))
    sim.set_workload_pods(pods)
    sim.set_typical_pods()
    specs = pods_to_specs(pods)
    out = sim.run_events(
        sim.init_state, specs, jnp.zeros(len(pods), jnp.int32),
        jnp.arange(len(pods), dtype=jnp.int32), jax.random.PRNGKey(2),
    )
    assert out.placed_node.shape[0] == len(pods)
    armed_total, true_total, line = calls
    assert armed_total > 0  # still armed: no periodic tick had disarmed it
    # the final line reports the PRE-padding count, not the padded
    # stream size the heartbeat was armed with
    assert true_total == len(pods) and armed_total >= true_total
    assert f"{true_total}/{true_total}" in line and "ev/s mean" in line


def test_chrome_counter_tracks(tmp_path):
    """write_chrome_trace emits `"ph": "C"` counter events for per-event
    series, laid across the scan spans' wall window, dense series
    strided down but always charting the final value."""
    import json as _json

    from tpusim.obs import Recorder, emitters

    rec = Recorder(enabled=True)
    with rec.span("typical_pods") as h:
        h.dispatched()
    with rec.span("scan", engine="table") as h:
        h.dispatched()
    tel = rec.snapshot(meta={})
    series = {
        "frag_gpu_milli": [float(i) for i in range(5000)],
        "used_gpu_milli": [1, 2, 3],
    }
    path = str(tmp_path / "trace.json")
    emitters.write_chrome_trace(path, tel.spans, series)
    data = _json.loads(open(path).read())
    counters = [e for e in data["traceEvents"] if e["ph"] == "C"]
    assert counters, "no counter events"
    frag = [e for e in counters if e["name"] == "frag_gpu_milli"]
    assert 0 < len(frag) <= emitters.MAX_COUNTER_POINTS + 1
    assert frag[-1]["args"]["frag_gpu_milli"] == 4999.0  # final value kept
    used = [e for e in counters if e["name"] == "used_gpu_milli"]
    assert [e["args"]["used_gpu_milli"] for e in used] == [1, 2, 3]
    # counter tracks sit inside the span window
    xs = [e for e in data["traceEvents"] if e["ph"] == "X"]
    t_lo = min(e["ts"] for e in xs)
    t_hi = max(e["ts"] + e["dur"] for e in xs)
    assert all(t_lo <= e["ts"] <= t_hi + 1 for e in counters)
    # emit_all threads the series through
    paths = emitters.emit_all(
        tel, trace=str(tmp_path / "t2.json"), counter_series=series
    )
    data2 = _json.loads(open(paths[0]).read())
    assert any(e["ph"] == "C" for e in data2["traceEvents"])


def test_bench_measure_protocol():
    """obs.bench.measure: one cold + N warm calls, min over warm."""
    from tpusim.obs import bench

    calls = []
    m = bench.measure(lambda: calls.append(1), warm_runs=3)
    assert len(calls) == 4
    assert m["min_s"] == min(m["samples_s"]) and len(m["samples_s"]) == 3
    cw = bench.measure_cold_warm(lambda: calls.append(1))
    assert "cold_s" in cw and "warm_s" in cw
    assert bench.round_row({"a": 1.23456, "b": [1.23456], "c": "x"}) == {
        "a": 1.235, "b": [1.235], "c": "x"
    }
