"""The self-healing supervisor (ISSUE 13): respawn-on-exit under capped
backoff, the crash-loop circuit breaker, and the queue-depth autoscale
policy — the whole state machine driven with FAKE children and FAKE
time (poll(now=...)), so tier-1 spawns no processes and sleeps never.
The process-spawning acceptance (respawn + breaker over real kill -9'd
workers) is the slow-marked WAN smoke in tests/test_transfer.py /
`make fleet-wan-smoke`.
"""

import itertools
import signal

import pytest

from tpusim.svc.supervisor import Supervisor

_PIDS = itertools.count(1000)


class FakeProc:
    """A Popen stand-in whose death the test scripts."""

    def __init__(self, ignore_term=False):
        self.pid = next(_PIDS)
        self.rc = None
        self.signals = []
        self.ignore_term = ignore_term

    def poll(self):
        return self.rc

    def die(self, rc=1):
        self.rc = rc

    def send_signal(self, sig):
        self.signals.append(sig)
        if sig == signal.SIGTERM and not self.ignore_term:
            self.rc = -int(signal.SIGTERM)

    def kill(self):
        self.rc = -int(signal.SIGKILL)

    def wait(self, timeout=None):
        if self.rc is None:
            raise TimeoutError("fake child ignoring SIGTERM")
        return self.rc


def _sup(n=2, **kw):
    spawned = []

    def spawn(_i):
        p = FakeProc()
        spawned.append(p)
        return p

    kw.setdefault("backoff_base_s", 0.5)
    kw.setdefault("healthy_after_s", 5.0)
    sup = Supervisor(spawn, n, **kw)
    return sup, spawned


def test_start_spawns_base_fleet():
    sup, spawned = _sup(3)
    sup.start(now=0.0)
    assert len(spawned) == 3 and sup.alive() == 3
    assert sup.counters["spawns"] == 3
    assert sup.counters["respawns"] == 0  # initial spawns are not respawns
    d = sup.describe()
    assert d["workers"] == 3 and d["alive"] == 3
    assert d["breaker"]["state"] == "closed"
    ok, fields = sup.healthy()
    assert ok and fields["supervisor_breaker"] == "closed"


def test_respawn_with_capped_backoff():
    sup, spawned = _sup(1, breaker_k=50)
    sup.start(now=0.0)
    # fast exit #1: respawned immediately, backoff armed at base
    spawned[0].die(3)
    ev = sup.poll(now=1.0)
    assert ev["reaped"] == [spawned[0].pid]
    assert len(ev["spawned"]) == 1 and sup.alive() == 1
    assert sup.counters["respawns"] == 1
    # fast exit #2 inside the backoff window: NOT respawned yet
    spawned[1].die(3)
    ev = sup.poll(now=1.2)
    assert ev["spawned"] == [] and sup.alive() == 0
    # past the backoff: respawned, delay doubled for the next one
    ev = sup.poll(now=2.0)
    assert len(ev["spawned"]) == 1 and sup.alive() == 1
    assert sup.describe()["consecutive_fast_exits"] == 2
    assert sup.describe()["respawn_backoff_s"] == 1.0  # 0.5 * 2^1
    # a long-lived child resets the schedule
    spawned[-1].die(0)
    sup.poll(now=100.0)  # lived ~98s > healthy_after_s
    assert sup.describe()["consecutive_fast_exits"] == 0
    assert sup.describe()["respawn_backoff_s"] == 0.0


def test_backoff_is_capped():
    """Six consecutive fast exits: the respawn delay doubles 0.5 → 1 →
    2 → 4 and pins at the cap. Poll times chosen so every cycle both
    reaps a fast exit (lifetime < healthy_after_s) and lands past the
    previous backoff gate."""
    sup, spawned = _sup(1, breaker_k=500, backoff_cap_s=4.0)
    sup.start(now=0.0)
    for t in (1.0, 2.0, 4.0, 7.0, 11.5, 16.0):
        spawned[-1].die(1)
        sup.poll(now=t)
        assert sup.alive() == 1, f"not respawned by t={t}"
    assert sup.describe()["consecutive_fast_exits"] == 6
    assert sup.describe()["respawn_backoff_s"] == 4.0  # capped


def test_breaker_trips_and_resets():
    sup, spawned = _sup(1, breaker_k=3, breaker_window_s=1000.0)
    sup.start(now=0.0)
    now = 0.0
    # three fast crash/respawn cycles fill the window
    for i in range(3):
        spawned[-1].die(1)
        now += 10.0
        ev = sup.poll(now=now)
        assert len(ev["spawned"]) == 1
    assert sup.counters["respawns"] == 3
    # the 4th crash meets an exhausted budget: breaker opens, NO spawn
    spawned[-1].die(1)
    now += 10.0
    ev = sup.poll(now=now)
    assert ev["breaker_open"] and ev["spawned"] == []
    assert sup.alive() == 0
    d = sup.describe()
    assert d["breaker"]["state"] == "open" and d["breaker"]["trips"] == 1
    assert "crash loop" in d["breaker"]["reason"]
    ok, fields = sup.healthy()
    assert not ok
    assert fields["supervisor_breaker"] == "open"
    assert "crash loop" in fields["supervisor_breaker_reason"]
    # further polls stay quiet (no spinning)
    ev = sup.poll(now=now + 100.0)
    assert ev["spawned"] == [] and sup.counters["respawns"] == 3
    # operator re-arms
    sup.reset_breaker()
    ev = sup.poll(now=now + 101.0)
    assert len(ev["spawned"]) == 1 and sup.alive() == 1
    assert sup.healthy()[0]


def test_breaker_window_slides():
    """Respawns spread WIDER than the window never trip the breaker."""
    sup, spawned = _sup(1, breaker_k=3, breaker_window_s=5.0)
    sup.start(now=0.0)
    now = 0.0
    for _ in range(10):
        spawned[-1].die(1)
        now += 10.0  # each respawn 10 s apart >> the 5 s window
        ev = sup.poll(now=now)
        assert len(ev["spawned"]) == 1, "breaker must not trip"
    assert sup.describe()["breaker"]["state"] == "closed"
    assert sup.counters["respawns"] == 10


def test_autoscale_up_to_max_and_down_to_base():
    depth = {"n": 0}
    sup, spawned = _sup(
        1, max_workers=3, load_fn=lambda: depth["n"],
        depth_per_worker=2, scale_idle_s=10.0, scale_cooldown_s=1.0,
    )
    sup.start(now=0.0)
    assert sup.alive() == 1
    # backlog: 10 queued > 2/worker -> scale up one per cooldown, to max
    depth["n"] = 10
    sup.poll(now=1.0)
    assert sup.alive() == 2 and sup.counters["scale_ups"] == 1
    sup.poll(now=1.5)  # inside the cooldown: no change
    assert sup.alive() == 2
    sup.poll(now=3.0)
    assert sup.alive() == 3
    sup.poll(now=5.0)  # at max: never beyond
    assert sup.alive() == 3 and sup.counters["scale_ups"] == 2
    # idle queue: after scale_idle_s, drain ONE gracefully per cycle
    depth["n"] = 0
    sup.poll(now=6.0)  # idle clock starts
    assert sup.alive() == 3
    sup.poll(now=17.0)  # 11 s idle > 10 s
    assert sup.counters["scale_downs"] == 1
    draining = [p for p in spawned if signal.SIGTERM in p.signals]
    assert len(draining) == 1
    # the drained child exits; it is reaped WITHOUT a respawn
    sup.poll(now=18.0)
    assert sup.alive() == 2
    assert sup.counters["respawns"] == 0
    sup.poll(now=29.0)
    sup.poll(now=30.0)
    assert sup.alive() == 1  # back to base, never below
    sup.poll(now=45.0)
    assert sup.alive() == 1 and sup.counters["scale_downs"] == 2


def test_on_exit_reports_crashes_not_drains():
    released = []
    depth = {"n": 5}
    sup, spawned = _sup(
        1, max_workers=2, load_fn=lambda: depth["n"],
        depth_per_worker=2, scale_idle_s=1.0, scale_cooldown_s=0.5,
        on_exit=released.append,
    )
    sup.start(now=0.0)
    sup.poll(now=1.0)  # scale up
    assert sup.alive() == 2
    crash = spawned[0]
    crash.die(9)
    sup.poll(now=2.0)
    assert released == [crash.pid]  # crashed child: leases released
    depth["n"] = 0
    sup.poll(now=3.0)
    sup.poll(now=5.0)  # idle -> drain the surplus child
    sup.poll(now=6.0)
    assert sup.counters["scale_downs"] == 1
    assert len(released) == 1  # the DRAINED child is not a crash


def test_stop_escalates_to_kill():
    sup, spawned = _sup(2)
    sup.start(now=0.0)
    spawned[0].ignore_term = True
    sup.stop(timeout=0.3)
    assert sup.alive() == 0
    assert spawned[0].rc == -int(signal.SIGKILL)  # escalated
    assert spawned[1].rc == -int(signal.SIGTERM)  # went gracefully


def test_constructor_validation():
    with pytest.raises(ValueError):
        Supervisor(lambda i: FakeProc(), 0)
    with pytest.raises(ValueError):
        Supervisor(lambda i: FakeProc(), 3, max_workers=2)
