"""Golden-value tests of the fragmentation math.

Expected numbers are the asserted values of pkg/utils/frag_test.go (the
reference's correctness oracle): TestNodeGpuShareFragAmount[Score],
TestNodeGpuShareFragAmountWithNonGpu, TestGetGpuFragMilliByNodeResAndPodRes,
TestNodeGpuFragAmountBellman_EightGpu.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import (
    FRAG_SCORE_GOLDENS,
    frag_golden_score,
    typical_pods_gpu,
    typical_pods_with_nongpu,
    typical_rows_gpu_host,
)
from tpusim.constants import GPU_MODEL_IDS, Q4_LACK_CPU
from tpusim.ops import frag
from tpusim.ops.resource import gpu_frag_milli
from tpusim.types import make_typical_pods


def node(cpu_left, gpus, gpu_type):
    g = np.zeros(8, np.int32)
    g[: len(gpus)] = gpus
    return jnp.int32(cpu_left), jnp.asarray(g), jnp.int32(GPU_MODEL_IDS[gpu_type])


def score(cpu_left, gpus, gpu_type, tp):
    c, g, t = node(cpu_left, gpus, gpu_type)
    return float(frag.node_frag_score(c, g, t, tp))


class TestNodeGpuShareFragAmountScore:
    # frag_test.go:100-121 / 142-163 — the golden cases live in
    # fixtures.FRAG_SCORE_GOLDENS, shared with the on-TPU lane
    @pytest.mark.parametrize(
        "case", FRAG_SCORE_GOLDENS, ids=lambda c: f"{c[2]}-{c[0]}cpu"
    )
    def test_golden_scores(self, case):
        actual, expected = frag_golden_score(case)
        assert actual == pytest.approx(expected, abs=0.05), case

    def test_single_spec_lack_cpu(self):
        tp = make_typical_pods([(6000, 465, 1, 0, 9.33 / 100)])
        c, g, t = node(1000, [200, 1000, 1000, 500], "1080")
        assert int(frag.frag_class(c, g, t, tp)[0]) == Q4_LACK_CPU
        assert int(g.sum()) == 2700
        assert score(1000, [200, 1000, 1000, 500], "1080", tp) == pytest.approx(
            251.91, abs=0.01
        )


# The with-nongpu distribution cases (frag_test.go:123-140) are covered by
# the "nongpu" rows of FRAG_SCORE_GOLDENS above.


class TestGetGpuFragMilli:
    # frag_test.go:165-185
    def test_cases(self):
        g1 = jnp.asarray(np.array([200, 1000, 1000, 500, 0, 0, 0, 0], np.int32))
        assert int(gpu_frag_milli(g1, jnp.int32(1000))) == 700
        full4 = jnp.asarray(
            np.array([1000, 1000, 1000, 1000, 0, 0, 0, 0], np.int32)
        )
        assert int(gpu_frag_milli(full4, jnp.int32(1000))) == 0
        full8 = jnp.asarray(np.full(8, 1000, np.int32))
        assert int(gpu_frag_milli(full8, jnp.int32(1000))) == 0
        assert int(gpu_frag_milli(g1, jnp.int32(200))) == 0


def test_bellman_eight_gpu():
    # frag_test.go:89-98: node with 78000 mCPU, 8 GPUs [6x1000, 535, 70],
    # V100M32, 35-spec distribution → 160.73
    rows = typical_rows_gpu_host()
    val = frag.node_frag_bellman(
        (78000, [1000] * 6 + [535, 70], GPU_MODEL_IDS["V100M32"]), rows
    )
    assert val == pytest.approx(160.73, abs=0.05)


def test_cluster_report_shapes():
    from tpusim.types import make_node_state

    tp = typical_pods_gpu()
    state = make_node_state(
        cpu_cap=[64000, 32000],
        mem_cap=[262144, 131072],
        gpu_cnt=[4, 0],
        gpu_type=[GPU_MODEL_IDS["1080"], -1],
    )
    amounts, frag_milli, frag_ratio, q124 = frag.cluster_frag_report(state, tp)
    assert amounts.shape == (7,)
    # all-idle 4x1080 node: frag == the 3802.40 golden value; CPU node adds 0
    assert float(frag_milli) == pytest.approx(
        frag.frag_sum_except_q3(
            frag.node_frag_amounts(
                jnp.int32(64000),
                jnp.asarray(np.array([1000] * 4 + [0] * 4, np.int32)),
                jnp.int32(GPU_MODEL_IDS["1080"]),
                tp,
            )
        ),
        rel=1e-5,
    )


def test_bellman_optimized_matches_naive():
    """The canonical-sorted/fit-count Bellman must equal the direct
    transcription of the definition on randomized states."""
    import numpy as np

    from tests.fixtures import typical_rows_gpu_host
    from tpusim.ops.frag import _node_frag_bellman_naive, node_frag_bellman

    t = typical_rows_gpu_host()
    rng = np.random.default_rng(5)
    for _ in range(20):
        g = tuple(int(x) for x in rng.choice([0, 100, 250, 500, 750, 1000], 8))
        node = (
            int(rng.choice([2000, 8000, 32000, 64000])),
            g,
            int(rng.integers(-1, 4)),
        )
        assert abs(
            node_frag_bellman(node, t) - _node_frag_bellman_naive(node, t)
        ) < 1e-9


def test_bellman_zero_milli_multi_gpu_pod():
    """A degenerate typical pod (gpu_num>0, gpu_milli==0) must not crash and
    must match the naive oracle."""
    from tpusim.ops.frag import _node_frag_bellman_naive, node_frag_bellman

    t = [(4000, 0, 2, 0, 0.5), (8000, 500, 1, 0, 0.5)]
    node = (16000, (1000, 1000, 500, 0, 0, 0, 0, 0), 1)
    assert abs(
        node_frag_bellman(node, t) - _node_frag_bellman_naive(node, t)
    ) < 1e-9
