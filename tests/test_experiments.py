"""Experiment-harness tests: run.py → log → analysis CSVs → merge → plots
(ref: scripts/generate_config_and_run.py + scripts/analysis.py +
experiments/analysis/merge_*.py, exercised on a tiny synthetic trace)."""

import csv
import importlib.util
import os
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
EXP = REPO / "experiments"


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _write_tiny_trace(dirpath: Path):
    node_csv = dirpath / "nodes.csv"
    pod_csv = dirpath / "tiny_trace.csv"
    with open(node_csv, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["sn", "cpu_milli", "memory_mib", "gpu", "model"])
        w.writerow(["n-0", 32000, 65536, 2, "V100M16"])
        w.writerow(["n-1", 64000, 131072, 4, "A100"])
    with open(pod_csv, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(
            [
                "name",
                "cpu_milli",
                "memory_mib",
                "num_gpu",
                "gpu_milli",
                "gpu_spec",
                "qos",
                "pod_phase",
                "creation_time",
                "deletion_time",
                "scheduled_time",
            ]
        )
        for i in range(8):
            w.writerow(
                [f"pod-{i}", 2000, 4096, 1, 500 if i % 2 else 1000, "", "LS", "Running", 0, 0, 0]
            )
    return node_csv, pod_csv


def test_run_analysis_merge_plot(tmp_path):
    run = _load("exp_run", EXP / "run.py")
    node_csv, pod_csv = _write_tiny_trace(tmp_path)
    outdir = tmp_path / "data" / "tiny_trace" / "06-FGD" / "1.0" / "42"
    args = run.get_args(
        [
            "-d",
            str(outdir),
            "-f",
            str(pod_csv),
            "--node-trace",
            str(node_csv),
            "-FGD",
            "1000",
            "-gpusel",
            "FGDScore",
            "--emit-configs",
        ]
    )
    result = run.run_experiment(args)
    assert result["summary"]["unscheduled"] == 0
    assert (outdir / "simon.log").is_file()

    # a second policy for the cross-policy power deliverable below
    outdir2 = tmp_path / "data" / "tiny_trace" / "05-BestFit" / "1.0" / "42"
    args2 = run.get_args(
        [
            "-d", str(outdir2), "-f", str(pod_csv),
            "--node-trace", str(node_csv), "-BestFit", "1000",
        ]
    )
    run.run_experiment(args2)
    # per-event series parsed back out of the log
    assert len(result["allo"]["used_gpu_milli"]) == 8
    assert result["allo"]["used_gpu_milli"][-1] == 6000  # 4×1000 + 4×500
    assert result["cdol"]["event"] == ["create"] * 8
    assert result["cdol"]["cum_pod"][-1] == 8
    # the cluster-analysis block made it into the summary row
    assert result["summary"]["milli_gpu_init_schedule"] == 100.0
    # emit-configs wrote the reproducible YAML pair
    assert list(outdir.glob("cc_md*.yaml")) and list(outdir.glob("sc_md*.yaml"))

    # merge into discrete tables
    merge = _load("exp_merge", EXP / "merge.py")
    results_dir = tmp_path / "results"
    merge.merge(tmp_path / "data", results_dir)
    with open(results_dir / "analysis_allo_discrete.csv", newline="") as f:
        rows = list(csv.DictReader(f))
    assert rows[0]["workload"] == "tiny_trace"
    assert rows[0]["sc_policy"] == "05-BestFit"
    assert float(rows[0]["100"]) == 100.0  # fully allocated at 100% load

    # power/usage/failed merges (the fork's notebook-1 parse, round 4)
    with open(results_dir / "analysis_pwr_discrete.csv", newline="") as f:
        pwr_rows = list(csv.DictReader(f))
    # one row per experiment per series, cluster = cpu + gpu at each sample
    by_series = {
        r["series"]: r for r in pwr_rows if r["sc_policy"] == "06-FGD"
    }
    assert set(by_series) == {"cluster", "cpu", "gpu"}
    assert float(by_series["cluster"]["100"]) == pytest.approx(
        float(by_series["cpu"]["100"]) + float(by_series["gpu"]["100"]), abs=0.05
    )
    with open(results_dir / "analysis_usage_discrete.csv", newline="") as f:
        usage_rows = list(csv.DictReader(f))
    # all 8 tiny pods schedule -> used == arrived at 100% load
    assert float(usage_rows[0]["100"]) == pytest.approx(1.0, abs=0.01)
    assert (results_dir / "analysis_failed_discrete.csv").is_file()

    # power deliverable: figures + tables from the merged artifact alone
    power = _load("exp_power", EXP / "power.py")
    power_dir = tmp_path / "power"
    sys.argv = [
        "power.py", "--merged", str(results_dir), "--out", str(power_dir)
    ]
    power.main()
    assert (power_dir / "power_savings_tiny_trace.png").is_file()
    assert (power_dir / "usage_efficiency_tiny_trace.png").is_file()
    assert (power_dir / "failed_relative_tiny_trace.png").is_file()
    md = (power_dir / "power_tables.md").read_text()
    assert "GRAR" in md and "06-FGD" in md and "05-BestFit" in md
    tex = (power_dir / "power_tables.tex").read_text()
    assert "\\begin{tabular}" in tex and "Savings" in tex

    # trace families with percentage suffixes must emit LaTeX-safe headers
    # (a raw % would comment out the rest of the header row)
    power.emit_tables(
        {"openb_pod_list_cpu": {"06-FGD": {"050": 0.95, "100": 0.97}}},
        {},
        power_dir,
    )
    tex2 = (power_dir / "power_tables.tex").read_text()
    assert "GRAR (050\\%)" in tex2
    assert "(050%)" not in tex2

    # plots render from the merged tables
    plot = _load("exp_plot", EXP / "plot" / "plot_openb.py")
    figdir = tmp_path / "figures"
    sys.argv = [
        "plot_openb.py",
        "--results",
        str(results_dir),
        "--out-dir",
        str(figdir),
        "--workload",
        "tiny_trace",
    ]
    plot.main()
    assert (figdir / "openb_alloc.png").is_file()

    # compare tool runs over the merged tables (no reference rows for the
    # tiny trace — prints ours-only cells and says so)
    import contextlib
    import io

    cmp_mod = _load("exp_compare", EXP / "compare.py")
    # the tiny workload tops out at 100% arrived load, so compare at 100
    sys.argv = ["compare.py", "--merged", str(results_dir), "--at", "100"]
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        cmp_mod.main()
    out = buf.getvalue()
    assert "tiny_trace" in out and "FGD" in out
    assert "100.00" in out  # the fully-allocated @100 cell
    assert "no overlapping reference cells" in out


def test_analysis_lanes_byte_identical(tmp_path):
    """The direct array->CSV lane (default) and the log-reparse lane
    (--analysis-from-log) must write byte-identical CSV families — on a
    trace exercising failures (an unfittable pod), deletions
    (deletion_time + --use-timestamps), and the failed-create rollback
    calculus (the --engine knob also gets a forced-table pass here)."""
    run = _load("exp_run2", EXP / "run.py")
    node_csv, _ = _write_tiny_trace(tmp_path)
    pod_csv = tmp_path / "mix_trace.csv"
    with open(pod_csv, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(
            ["name", "cpu_milli", "memory_mib", "num_gpu", "gpu_milli",
             "gpu_spec", "qos", "pod_phase", "creation_time",
             "deletion_time", "scheduled_time"]
        )
        for i in range(10):
            w.writerow([f"pod-{i}", 2000, 4096, 1, 500, "", "LS",
                        "Running", i, i + 20 if i % 3 == 0 else 0, 0])
        # unfittable: more CPU than any node has
        w.writerow(["pod-big", 99000000, 4096, 0, 0, "", "LS", "Running",
                    5, 0, 0])

    outs = {}
    for lane, extra in (("direct", ()), ("log", ("--analysis-from-log",))):
        outdir = tmp_path / lane
        run.run_experiment(run.get_args(
            ["-d", str(outdir), "-f", str(pod_csv), "--node-trace",
             str(node_csv), "-FGD", "1000", "-gpusel", "FGDScore",
             "--use-timestamps", "--engine", "table", *extra]
        ))
        outs[lane] = outdir
    files = sorted(
        p.name for p in outs["direct"].iterdir()
        if p.name.startswith("analysis")
    )
    assert "analysis_fail.csv" in files  # the unfittable pod failed
    for name in files:
        a = (outs["direct"] / name).read_bytes()
        b = (outs["log"] / name).read_bytes()
        assert a == b, f"{name} differs between analysis lanes"


def test_reused_simulator_lanes_stay_identical(tmp_path):
    """Calling run() twice on one Simulator must not double-count the
    direct-CSV stashes vs the log lane (ADVICE r4): both lanes reflect the
    LAST run only, byte-identically."""
    import sys

    sys.path.insert(0, str(EXP))
    from analysis import build_result_from_sim, parse_log

    from tpusim.io.trace import load_node_csv, load_pod_csv
    from tpusim.sim.driver import Simulator, SimulatorConfig

    node_csv, pod_csv = _write_tiny_trace(tmp_path)
    sim = Simulator(
        load_node_csv(str(node_csv)),
        SimulatorConfig(policies=(("FGDScore", 1000),), seed=1),
    )
    sim.set_workload_pods(load_pod_csv(str(pod_csv)))
    sim.run()
    sim.finish()
    sim.run()  # reuse: stashes and log must reset
    sim.finish()
    assert len(sim.event_reports) == 1
    log_path = tmp_path / "simon.log"
    log_path.write_text(sim.log.dump())
    direct = build_result_from_sim(sim)
    parsed = parse_log(str(log_path))
    assert direct["frag"] == parsed["frag"]
    assert direct["allo"] == parsed["allo"]
    assert direct["summary"]["unscheduled"] == parsed["summary"]["unscheduled"]


def test_generate_run_scripts(capsys):
    gen = _load("exp_gen", EXP / "generate_run_scripts.py")
    sys.argv = [
        "generate_run_scripts.py",
        "--seeds",
        "2",
        "--traces",
        "openb_pod_list_default",
        "--methods",
        "06-FGD",
        "01-Random",
    ]
    gen.main()
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 4  # 1 trace × 2 methods × 2 seeds
    assert all("experiments/run.py" in l for l in lines)
    assert any("-FGD 1000" in l and "-tuneseed 43" in l for l in lines)


def test_analysis_stop_marker(tmp_path):
    """Lines after `there are N unscheduled pods` are ignored, matching the
    reference parser's break (scripts/analysis.py log_to_csv)."""
    ana = _load("exp_ana", EXP / "analysis.py")
    log = tmp_path / "x.log"
    log.write_text(
        'time="t" level=info msg="[Report]; Frag amount: 10.00; Frag ratio: 5.00%; Q124 ratio: 1.00%; (origin)\\n"\n'
        'time="t" level=info msg="there are 3 unscheduled pods\\n"\n'
        'time="t" level=info msg="[Report]; Frag amount: 99.00; Frag ratio: 9.00%; Q124 ratio: 9.00%; (origin)\\n"\n'
    )
    out = ana.parse_log(str(log))
    assert out["summary"]["unscheduled"] == 3
    assert out["frag"]["origin_milli"] == [10.0]


def test_bellman_series_cache_identical(tmp_path, monkeypatch):
    """The persistent Bellman-series cache (content-keyed, like the XLA
    compile cache) must reproduce uncached results byte-identically — incl.
    multi-stage experiments, where a first-call cache hit replays its
    inputs before any later stage evaluates (memo-order dependence)."""
    run = _load("exp_run_bc", EXP / "run.py")
    node_csv, pod_csv = _write_tiny_trace(tmp_path)
    base = ["-f", str(pod_csv), "--node-trace", str(node_csv),
            "-FGD", "1000", "-gpusel", "FGDScore",
            "--workload-inflation-ratio", "1.6"]  # second bellman stage

    outs = {}
    # warm2 exercises the second-warm-run ordering hazard: a first-call
    # hit must not let LATER stages read/write the cache (their values
    # embed the warmed memo's evaluation order)
    for label, cache in (("nocache", ""), ("cold", str(tmp_path / "bc")),
                         ("warm", str(tmp_path / "bc")),
                         ("warm2", str(tmp_path / "bc"))):
        monkeypatch.setenv("TPUSIM_BELLMAN_CACHE", cache)
        outdir = tmp_path / label
        run.run_experiment(run.get_args(["-d", str(outdir)] + base))
        outs[label] = outdir
    entries = list((tmp_path / "bc").glob("*.npy"))
    assert len(entries) == 1, "only the FIRST stage's series may be cached"
    for name in ("analysis.csv", "analysis_frag.csv", "analysis_allo.csv"):
        ref = (outs["nocache"] / name).read_bytes()
        for label in ("cold", "warm", "warm2"):
            assert (outs[label] / name).read_bytes() == ref, f"{name} ({label})"
