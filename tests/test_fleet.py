"""The resilient fleet (ISSUE 12): leased job ownership, orphan
stealing, and the kill-tolerant multi-worker service plane.

The tier-1 slice is pure host-side protocol — no device dispatch, no
compiles (~2 s):

  1. lease files: signed round-trip, torn/edited files skipped+DELETED
     with a [Degrade] warning (the load_valid_checkpoint pattern),
     foreign headers rejected, clock-skew margin honored
     (TPUSIM_LEASE_SKEW_S);
  2. JobQueue claim/steal: claim stamps owner + deadline, expired
     leases are stolen back to the FRONT of the queue in submission
     order, renew extends and reports lost leases, release_worker
     reclaims a known-dead worker instantly;
  3. duplicate completion of a stolen job is a silent dedup (the
     at-least-once/idempotent contract);
  4. per-family admission quotas: QuotaFull 429 + Retry-After naming
     the family, other families unaffected, depths surfaced in /queue;
  5. the claim handshake: spec_to_payload round-trips to the identical
     spec + digest; the FleetService register/claim/renew/complete
     protocol driven synchronously (no HTTP, no device) including the
     stolen-but-already-finished shortcut and coordinator-restart
     lease adoption;
  6. fleet /healthz degrading to 503 only when NO worker is live.

Slow (resume-smoke / `make fleet-chaos-smoke`): the mixed
fault/tune/weight batch through the REAL dispatch path
(lane-vs-standalone bit-identity), and the full 3-process kill -9
acceptance via gate.fleet_chaos_smoke.
"""

import json
import os
import time

import numpy as np
import pytest

from tpusim.io.trace import NodeRow, PodRow
from tpusim.svc import jobs as svc_jobs
from tpusim.svc import leases as svc_leases
from tpusim.svc.api import JobService, start_job_server
from tpusim.svc.batcher import JobQueue, QuotaFull, QueueFull
from tpusim.svc.fleet import FleetService
from tpusim.svc.worker import TraceRef, Worker

FAM = [["FGDScore", 1000], ["BestFitScore", 500]]


def _mk_cluster(rng, n=16):
    return [
        NodeRow(f"n{i:03d}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], n))
    ]


def _mk_pods(rng, n=40):
    out = []
    for i in range(n):
        gpu = int(rng.choice([0, 1, 2]))
        milli = 1000 if gpu > 1 else int(rng.choice([0, 300, 500, 1000]))
        if gpu == 0:
            milli = 0
        out.append(
            PodRow(f"p{i:04d}", int(rng.choice([1000, 2000, 4000])), 2048,
                   gpu, milli)
        )
    return out


@pytest.fixture(scope="module")
def trace():
    rng = np.random.default_rng(3)
    nodes, pods = _mk_cluster(rng), _mk_pods(rng)
    return TraceRef(
        "default", nodes, pods, svc_jobs.trace_digest(nodes, pods)
    )


def _spec(i=0, fault=False, tune=0.0):
    doc = {"policies": FAM, "weights": [1000 + i, 500], "seed": 42,
           "tune": tune}
    if fault:
        doc["fault"] = {"mtbf_events": 5.0, "seed": 7 + i}
    return svc_jobs.validate_job(doc)


def _submit(queue, trace, i=0, **kw):
    spec = _spec(i, **kw)
    return queue.submit(spec, svc_jobs.job_digest(spec, trace.digest))


# ---------------------------------------------------------------------------
# 1. lease files
# ---------------------------------------------------------------------------


def test_lease_file_roundtrip_and_torn_degrade(tmp_path):
    """Signed round-trip, then the degrade ladder: a torn/edited lease
    is skipped AND deleted with a [Degrade] callback — never trusted,
    never fatal, never shadowing re-claims. (Merged from two cases in
    the ISSUE 13 tier-1 trim.)"""
    art = str(tmp_path)
    path = svc_leases.write_lease(
        art, "d" * 64, "w001", 1234, 1000.5, ["d" * 64, "e" * 64]
    )
    assert path.endswith(".lease.json")
    doc = svc_leases.read_lease(art, "d" * 64)
    assert doc["worker"] == "w001" and doc["pid"] == 1234
    assert doc["deadline_unix"] == 1000.5
    assert doc["members"] == ["d" * 64, "e" * 64]
    assert [d for d, _ in svc_leases.scan_leases(art)] == ["d" * 64]
    svc_leases.delete_lease(art, "d" * 64)
    assert svc_leases.read_lease(art, "d" * 64) is None
    svc_leases.write_lease(art, "a" * 64, "w001", 1, 99.0, ["a" * 64])
    path = svc_leases.lease_path(art, "a" * 64)
    with open(path) as f:
        lines = f.read().splitlines()
    # edit the payload without updating the signed header digest
    doc = json.loads(lines[1])
    doc["deadline_unix"] = 10**9  # an attacker-immortal lease
    with open(path, "w") as f:
        f.write(lines[0] + "\n")
        f.write(json.dumps(doc, sort_keys=True) + "\n")
    skipped = []
    assert svc_leases.read_lease(
        art, "a" * 64, on_skip=lambda p, e: skipped.append((p, e))
    ) is None
    assert skipped and not os.path.isfile(path)

    # truncated file: same fate
    svc_leases.write_lease(art, "b" * 64, "w001", 1, 99.0, ["b" * 64])
    path_b = svc_leases.lease_path(art, "b" * 64)
    with open(path_b, "w") as f:
        f.write('{"schema": "tpusim-svc-lease/1"')
    assert svc_leases.read_lease(art, "b" * 64,
                                 on_skip=lambda p, e: None) is None
    assert not os.path.isfile(path_b)

    # foreign header (job digest mismatch under a renamed file)
    svc_leases.write_lease(art, "c" * 64, "w001", 1, 99.0, ["c" * 64])
    os.replace(svc_leases.lease_path(art, "c" * 64),
               svc_leases.lease_path(art, "f" * 64))
    assert svc_leases.read_lease(art, "f" * 64,
                                 on_skip=lambda p, e: None) is None


def test_lease_expiry_skew_margin(monkeypatch):
    lease = {"worker": "w", "deadline_unix": 100.0}
    monkeypatch.setenv("TPUSIM_LEASE_SKEW_S", "30")
    assert svc_leases.lease_skew_s() == 30.0
    # within the margin: a clock 29 s past the deadline must NOT steal
    assert not svc_leases.lease_expired(lease, now=129.0)
    assert svc_leases.lease_expired(lease, now=131.0)
    # explicit skew overrides the env
    assert svc_leases.lease_expired(lease, now=101.0, skew_s=0.5)


def test_lease_env_knobs_fail_loudly(monkeypatch):
    """ISSUE 13 satellite: an unparseable/out-of-range float env knob
    raises at read with a message NAMING the variable — a typo'd skew
    must not silently make every lease immortal or instantly
    stealable."""
    monkeypatch.setenv("TPUSIM_LEASE_SKEW_S", "not-a-number")
    with pytest.raises(ValueError, match="TPUSIM_LEASE_SKEW_S"):
        svc_leases.lease_skew_s()
    monkeypatch.setenv("TPUSIM_LEASE_SKEW_S", "-3")
    with pytest.raises(ValueError, match="TPUSIM_LEASE_SKEW_S"):
        svc_leases.lease_skew_s()
    monkeypatch.setenv("TPUSIM_LEASE_SKEW_S", "inf")
    with pytest.raises(ValueError, match="TPUSIM_LEASE_SKEW_S"):
        svc_leases.lease_skew_s()
    monkeypatch.delenv("TPUSIM_LEASE_SKEW_S")
    assert svc_leases.lease_skew_s() == 2.0

    monkeypatch.setenv("TPUSIM_LEASE_S", "ten")
    with pytest.raises(ValueError, match="TPUSIM_LEASE_S"):
        svc_leases.default_lease_s()
    monkeypatch.setenv("TPUSIM_LEASE_S", "0")
    with pytest.raises(ValueError, match="TPUSIM_LEASE_S"):
        svc_leases.default_lease_s()
    monkeypatch.setenv("TPUSIM_LEASE_S", "7.5")
    assert svc_leases.default_lease_s() == 7.5
    # the queue picks the env default up (no --lease-s override)
    assert JobQueue(maxsize=4).lease_s == 7.5
    monkeypatch.delenv("TPUSIM_LEASE_S")
    assert svc_leases.default_lease_s() == svc_leases.DEFAULT_LEASE_S


# ---------------------------------------------------------------------------
# 2./3. claim, steal, renew, duplicate completion
# ---------------------------------------------------------------------------


def test_claim_steal_ordering_and_renew(trace):
    queue = JobQueue(maxsize=16, lane_width=2, lease_s=0.5)
    jobs = [_submit(queue, trace, i) for i in range(5)]

    batch = queue.claim_batch("w1", timeout=0)
    assert [j.seq for j in batch] == [1, 2]
    assert all(j.worker == "w1" and j.status == "batched" for j in batch)
    assert all(j.lease_deadline_unix > time.time() for j in batch)
    assert len(queue.jobs_of_worker("w1")) == 2

    # not expired yet: nothing to steal
    assert queue.steal_expired() == []
    # renew keeps them alive past the original deadline
    renewed, lost = queue.renew("w1", [j.digest for j in batch])
    assert len(renewed) == 2 and not lost
    # another worker's renew owns nothing -> all lost
    _, lost = queue.renew("w2", [j.digest for j in batch])
    assert len(lost) == 2

    # force expiry: stolen back to the FRONT in submission order,
    # ahead of the younger queued jobs (seq 3..5)
    stolen = queue.steal_expired(now=time.time() + 10)
    assert [j.seq for j in stolen] == [1, 2]
    assert all(j.status == "queued" and not j.worker for j in stolen)
    assert all(j.stolen == 1 for j in stolen)
    nxt = queue.claim_batch("w2", timeout=0)
    assert [j.seq for j in nxt] == [1, 2]  # the orphans go first
    st = queue.stats()
    assert st["steals"] == 2 and st["lease_expired"] == 2

    # release_worker: instant reclaim for a known-dead worker
    stolen2 = queue.release_worker("w2")
    assert [j.seq for j in stolen2] == [1, 2]
    assert queue.stats()["steals"] == 4


def test_duplicate_completion_is_silent_dedup(trace):
    queue = JobQueue(maxsize=8, lane_width=1, lease_s=0.01)
    job = _submit(queue, trace, 0)
    [j] = queue.claim_batch("w1", timeout=0)
    # w1 stalls; the lease expires; w2 steals and completes
    queue.steal_expired(now=time.time() + 10)
    [j2] = queue.claim_batch("w2", timeout=0)
    assert j2 is job
    queue.mark_done(job, {"placed": 1})
    # the not-actually-dead w1 completes the SAME job later
    queue.mark_done(job, {"placed": 1})
    st = queue.stats()
    assert st["done"] == 1 and st["dup_completions"] == 1
    assert job.status == "done"
    # a late failure report can't un-done it either
    queue.mark_failed(job, "spurious")
    assert job.status == "done"
    assert queue.stats()["dup_completions"] == 2


# ---------------------------------------------------------------------------
# 4. per-family admission quotas
# ---------------------------------------------------------------------------


def test_family_quota_429(trace, tmp_path):
    queue = JobQueue(maxsize=16, lane_width=2, family_quota=2)
    _submit(queue, trace, 0)
    _submit(queue, trace, 1)
    with pytest.raises(QuotaFull) as exc:
        _submit(queue, trace, 2)
    assert exc.value.quota == 2
    assert exc.value.family.endswith("|nofault")
    assert isinstance(exc.value, QueueFull)  # same 429 surface
    # a DIFFERENT family (fault jobs batch separately) is unaffected
    _submit(queue, trace, 0, fault=True)
    st = queue.stats()
    assert st["quota_rejected"] == 1 and st["family_quota"] == 2
    assert sorted(st["families"].values()) == [1, 2]

    # the HTTP body names the family and carries Retry-After
    service = JobService(queue, None, {"default": trace}, str(tmp_path))
    resp = service.handle(
        "POST", "/jobs",
        json.dumps({"policies": FAM, "weights": [1003, 500],
                    "seed": 42}).encode(),
    )
    code, _, body = resp[0], resp[1], json.loads(resp[2].decode())
    headers = resp[3] if len(resp) > 3 else {}
    assert code == 429 and "family" in body
    assert headers.get("Retry-After")


def test_quota_rejection_is_not_prefix(trace, tmp_path):
    """A quota-full doc must not block LATER docs of other families in
    the same POST: the 429 body lists rejected_indices and the client
    retries exactly those (no starvation, no dropped docs)."""
    queue = JobQueue(maxsize=16, lane_width=2, family_quota=1)
    service = JobService(queue, None, {"default": trace}, str(tmp_path))
    docs = [
        {"policies": FAM, "weights": [1000, 500], "seed": 1},  # admits
        {"policies": FAM, "weights": [1001, 500], "seed": 2},  # quota
        {"policies": FAM, "weights": [1002, 500], "seed": 3,   # other
         "fault": {"mtbf_events": 5.0, "seed": 1}},            # family
    ]
    resp = service.handle("POST", "/jobs",
                          json.dumps({"jobs": docs}).encode())
    code, body = resp[0], json.loads(resp[2].decode())
    assert code == 429
    assert body["rejected_indices"] == [1]
    assert len(body["accepted"]) == 2  # doc 0 AND doc 2 admitted
    assert body["family"].endswith("|nofault")

    # the client-side retry arithmetic consumes rejected_indices
    from tpusim.svc import client as svc_client

    calls = []

    def fake_request(url, data=None, timeout=30.0, headers=None):
        calls.append(json.loads(data.decode()))
        if len(calls) == 1:
            return 429, {"Retry-After": "0"}, body
        return 202, {}, {"jobs": [{"id": "j2"}]}

    monkey_sleep = svc_client.time.sleep
    svc_client.time.sleep = lambda s: None
    svc_client._request, real = fake_request, svc_client._request
    try:
        accepted = svc_client.submit_jobs("http://x", docs)
    finally:
        svc_client._request = real
        svc_client.time.sleep = monkey_sleep
    assert len(accepted) == 3
    # the second POST carried ONLY the quota-rejected doc
    assert calls[1]["jobs"] == [docs[1]]


# ---------------------------------------------------------------------------
# 5. the claim handshake + FleetService protocol (no HTTP, no device)
# ---------------------------------------------------------------------------


def test_spec_to_payload_roundtrip(trace):
    for kw in ({}, {"fault": True}, {"tune": 0.7},
               {"fault": True, "tune": 1.2}):
        spec = _spec(3, **kw)
        payload = svc_jobs.spec_to_payload(spec)
        spec2 = svc_jobs.validate_job(payload)
        assert spec2 == spec
        assert (svc_jobs.job_digest(spec2, trace.digest)
                == svc_jobs.job_digest(spec, trace.digest))


def _fleet_stack(trace, tmp_path, lease_s=0.4, family_quota=0):
    queue = JobQueue(maxsize=32, lane_width=2, lease_s=lease_s,
                     family_quota=family_quota)
    service = JobService(queue, None, {"default": trace}, str(tmp_path))
    service.bucket = 512
    fleet = FleetService(service)
    service.fleet = fleet
    return queue, service, fleet


def _call(fleet, path, doc):
    resp = fleet.handle("POST", path, json.dumps(doc).encode())
    return resp[0], json.loads(resp[2].decode())


@pytest.mark.slow
def test_fleet_protocol_claim_steal_complete(trace, tmp_path):
    queue, service, fleet = _fleet_stack(trace, tmp_path)
    art = str(tmp_path)

    # unknown workers are told to re-register (the restart contract)
    code, doc = _call(fleet, "/workers/claim", {"worker": "ghost"})
    assert code == 409 and doc["register"]

    code, reg = _call(fleet, "/workers/register",
                      {"worker": "", "pid": 111, "host": "h1"})
    assert code == 200
    w1 = reg["worker"]
    assert reg["lane_width"] == 2 and reg["lease_s"] == queue.lease_s
    assert reg["traces"]["default"]["digest"] == trace.digest

    for i in range(4):
        service.submit_payload(
            {"policies": FAM, "weights": [1000 + i, 500], "seed": 42}
        )
    code, claim = _call(fleet, "/workers/claim", {"worker": w1})
    assert code == 200 and len(claim["jobs"]) == 2
    jd = claim["jobs"][0]
    # the wire spec revalidates to the identical digest
    spec = svc_jobs.validate_job(jd["spec"])
    assert svc_jobs.job_digest(spec, trace.digest) == jd["digest"]

    # the worker-side half: lease files staked, then one job finished
    members = [j["digest"] for j in claim["jobs"]]
    for d in members:
        svc_leases.write_lease(art, d, w1, 111,
                               claim["deadline_unix"], members)
    res = {"placed": 1, "job": members[0]}
    svc_jobs.write_result(art, members[0], res)
    code, comp = _call(fleet, "/workers/complete",
                       {"worker": w1, "done": [members[0]],
                        "dispatch_s": 1.5})
    assert code == 200 and comp["acked"] == 1
    assert queue.get_by_digest(members[0]).status == "done"
    assert fleet.registry.workers[w1].first_dispatch_s == 1.5

    # w1 dies holding members[1]; a second worker's claim steals it
    code, reg2 = _call(fleet, "/workers/register",
                       {"worker": "", "pid": 222, "host": "h2"})
    w2 = reg2["worker"]
    time.sleep(queue.lease_s + 0.05)
    code, claim2 = _call(fleet, "/workers/claim", {"worker": w2})
    got = [j["digest"] for j in claim2["jobs"]]
    assert members[1] in got  # the orphan rode the front of the queue
    assert [j for j in claim2["jobs"] if j["digest"] == members[1]][
        0]["stolen"] == 1
    # the dead owner's lease FILE was cleaned by the coordinator sweep
    assert svc_leases.read_lease(art, members[1]) is None
    assert queue.stats()["steals"] >= 1

    # completion reported without a result file on disk -> failed loudly
    # (mark_failed drops the digest mapping so a re-submit can retry —
    # hold the Job object to observe the terminal state)
    job_obj = queue.get_by_digest(members[1])
    code, comp2 = _call(fleet, "/workers/complete",
                        {"worker": w2, "done": [members[1]]})
    assert job_obj.status == "failed"
    assert "no valid signed result" in job_obj.error


@pytest.mark.slow
def test_stale_failure_report_cannot_kill_stolen_job(trace, tmp_path):
    """A stalled worker whose batch was stolen must not fail a job the
    thief is validly running — only the CURRENT owner's failure report
    lands. And a child the coordinator reaped is released instantly
    (release_dead), no lease wait."""
    queue, service, fleet = _fleet_stack(trace, tmp_path)
    _call(fleet, "/workers/register", {"worker": "wA", "pid": 71})
    _call(fleet, "/workers/register", {"worker": "wB", "pid": 72})
    service.submit_payload(
        {"policies": FAM, "weights": [4321, 500], "seed": 42}
    )
    code, claim = _call(fleet, "/workers/claim", {"worker": "wA"})
    d = claim["jobs"][0]["digest"]
    job = queue.get_by_digest(d)
    # wA stalls; lease expires; wB steals and is running it
    time.sleep(queue.lease_s + 0.05)
    code, claim2 = _call(fleet, "/workers/claim", {"worker": "wB"})
    assert [j["digest"] for j in claim2["jobs"]] == [d]
    # wA resumes and reports failure — a stale verdict, ignored
    code, comp = _call(fleet, "/workers/complete",
                       {"worker": "wA", "failed": {d: "stale crash"}})
    assert job.status == "running" or job.status == "batched"
    assert comp["dup"] == 1  # counted as a late duplicate, not acked
    # wB finishes normally
    svc_jobs.write_result(str(tmp_path), d, {"placed": 1, "job": d})
    code, comp = _call(fleet, "/workers/complete",
                       {"worker": "wB", "done": [d]})
    assert comp["acked"] == 1 and job.status == "done"

    # release_dead: a reaped child's jobs go back instantly
    service.submit_payload(
        {"policies": FAM, "weights": [4322, 500], "seed": 42}
    )
    code, claim3 = _call(fleet, "/workers/claim", {"worker": "wB"})
    assert len(claim3["jobs"]) == 1
    assert fleet.release_dead(72) == 1
    d3 = claim3["jobs"][0]["digest"]
    assert queue.get_by_digest(d3).status == "queued"
    assert fleet.release_dead(9999) == 0  # unknown pid: no-op


@pytest.mark.slow
def test_fleet_claim_shortcut_already_finished(trace, tmp_path):
    """A stolen job whose presumed-dead owner DID write the signed
    result is answered from disk at claim time — never re-run."""
    queue, service, fleet = _fleet_stack(trace, tmp_path)
    _call(fleet, "/workers/register", {"worker": "wA", "pid": 1})
    _call(fleet, "/workers/register", {"worker": "wB", "pid": 2})
    job = service.submit_payload(
        {"policies": FAM, "weights": [1234, 500], "seed": 42}
    )
    code, claim = _call(fleet, "/workers/claim", {"worker": "wA"})
    d = claim["jobs"][0]["digest"]
    # wA writes the result but dies before POSTing complete
    svc_jobs.write_result(str(tmp_path), d, {"placed": 1, "job": d})
    time.sleep(queue.lease_s + 0.05)
    code, claim2 = _call(fleet, "/workers/claim", {"worker": "wB"})
    assert claim2["jobs"] == []  # answered from disk, not re-handed
    assert queue.get_by_digest(d).status == "done"
    assert queue.stats()["dup_completions"] == 0


def test_coordinator_restart_adopts_live_leases(trace, tmp_path):
    """A coordinator restart under a LIVE worker re-attaches its lease
    instead of double-handing the batch out; an EXPIRED lease file is
    cleaned and its jobs stay stealable."""
    art = str(tmp_path)
    spec = _spec(9)
    digest = svc_jobs.job_digest(spec, trace.digest)
    payload = svc_jobs.spec_to_payload(spec)
    svc_jobs.write_job_spec(art, digest, payload)  # the PR 10 half
    svc_leases.write_lease(art, digest, "w-live", 999,
                           time.time() + 30.0, [digest])
    spec2 = _spec(10)
    digest2 = svc_jobs.job_digest(spec2, trace.digest)
    svc_jobs.write_job_spec(art, digest2, svc_jobs.spec_to_payload(spec2))
    svc_leases.write_lease(art, digest2, "w-dead", 998,
                           time.time() - 60.0, [digest2])

    # "restart": a fresh stack over the same artifact dir
    from tpusim.svc.api import recover_pending_jobs

    queue, service, fleet = _fleet_stack(trace, tmp_path)
    assert recover_pending_jobs(service) == 2
    adopted = fleet.adopt_leases()
    assert adopted == 1
    job = queue.get_by_digest(digest)
    assert job.status == "batched" and job.worker == "w-live"
    # the live owner's complete lands against the adopted claim
    svc_jobs.write_result(art, digest, {"placed": 1, "job": digest})
    code, comp = _call(fleet, "/workers/complete",
                       {"worker": "w-live", "done": [digest]})
    assert comp["acked"] == 1 and job.status == "done"
    # the expired lease: file cleaned, job still claimable
    assert svc_leases.read_lease(art, digest2) is None
    assert queue.get_by_digest(digest2).status == "queued"


# ---------------------------------------------------------------------------
# 6. fleet /healthz
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fleet_healthz_degrades_only_when_empty(trace, tmp_path):
    import urllib.error
    import urllib.request

    srv, service, worker = start_job_server(
        str(tmp_path), {"default": trace}, listen=":0", fleet=True,
        lease_s=0.3, recover=False,
    )
    try:
        assert worker is None
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(srv.url + "/healthz", timeout=5)
        assert exc.value.code == 503  # no worker live yet
        body = json.loads(exc.value.read().decode())
        assert body["ok"] is False and body["workers_live"] == 0

        service.fleet.registry.register("w1", 123, "h")
        with urllib.request.urlopen(srv.url + "/healthz", timeout=5) as r:
            body = json.loads(r.read().decode())
        assert r.status == 200 and body["ok"] is True
        # GET /workers lists the roster
        with urllib.request.urlopen(srv.url + "/workers", timeout=5) as r:
            body = json.loads(r.read().decode())
        assert "w1" in body["workers"]

        # the worker goes silent past the liveness window -> 503 again
        service.fleet.registry.workers["w1"].last_seen_unix -= 3600
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(srv.url + "/healthz", timeout=5)
        assert exc.value.code == 503
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# slow: real dispatch + the full chaos acceptance
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_mixed_fault_tune_batch_bit_identity(trace, tmp_path):
    """The ISSUE 12 chaos x tune lift through the WORKER dispatch path:
    one batch mixing fault seeds, tune factors, and weights runs one
    compiled scan, each lane bit-identical to the standalone run."""
    from tpusim.sim.driver import Simulator, SimulatorConfig

    queue = JobQueue(maxsize=16, lane_width=4)
    worker = Worker(queue, {"default": trace}, str(tmp_path),
                    lease_files=False)
    service = JobService(queue, worker, {"default": trace}, str(tmp_path))
    fault = {"mtbf_events": 12.0, "mttr_events": 15.0, "seed": 7,
             "backoff_base": 2, "backoff_cap": 16, "max_retries": 2,
             "queue_capacity": 16}
    docs = [
        {"policies": FAM, "weights": [1000, 500], "seed": 42,
         "tune": 0.0, "engine": "sequential",
         "fault": dict(fault, seed=11)},
        {"policies": FAM, "weights": [700, 300], "seed": 43,
         "tune": 0.5, "engine": "sequential",
         "fault": dict(fault, seed=13)},
        {"policies": FAM, "weights": [900, 100], "seed": 42,
         "tune": 0.3, "engine": "sequential",
         "fault": dict(fault, seed=17)},
    ]
    for d in docs:
        service.submit_payload(d)
    batch = queue.next_batch(timeout=0)
    assert len(batch) == 3  # ONE family despite three tunes
    worker.run_batch(batch)
    for d, job in zip(docs, batch):
        assert job.status == "done", job.error
        sim = Simulator(trace.nodes, SimulatorConfig(
            policies=tuple((n, w) for (n, _), w
                           in zip(FAM, d["weights"])),
            gpu_sel_method="best", seed=d["seed"],
            report_per_event=False, shuffle_pod=False,
            tuning_ratio=d["tune"], engine="sequential",
        ))
        sim.set_workload_pods(list(trace.pods))
        res = sim.run_with_faults(
            fault_cfg=svc_jobs.validate_job(d).fault_config()
        )
        assert job.result["placed_node"] == [
            int(x) for x in res.placed_node
        ]
        assert job.result["disruption"] == sim.last_disruption.as_dict()


@pytest.mark.slow
def test_fleet_chaos_acceptance(tmp_path):
    """The full ISSUE 12 acceptance: 3 worker processes, kill -9
    mid-batch, 100% completion byte-identical to a single-worker run,
    steal counters visible in /queue, warm joiner skips the compile —
    gate.fleet_chaos_smoke IS the harness (also `make
    fleet-chaos-smoke`)."""
    from tpusim.obs.gate import fleet_chaos_smoke

    ok, msgs = fleet_chaos_smoke(str(tmp_path))
    assert ok, "\n".join(msgs)
