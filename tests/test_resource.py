"""Resource-algebra tests (ref: pkg/type/resource_test.go semantics:
Flatten sort+pad, Sub packs least-free fitting GPUs first, Add returns
resources to given devices)."""

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.ops import resource as res
from tpusim.types import make_pod


def g(*vals):
    a = np.zeros(8, np.int32)
    a[: len(vals)] = vals
    return jnp.asarray(a)


def test_flatten_sorts_desc_and_pads():
    assert res.flatten_gpu_left(g(200, 1000, 1000, 500)).tolist() == [
        1000,
        1000,
        500,
        200,
        0,
        0,
        0,
        0,
    ]


def test_sub_packs_least_free_first():
    # share pod 300m goes to the tightest fitting device (500, idx 3)
    pod = make_pod(cpu=1000, gpu_milli=300, gpu_num=1)
    cpu, mem, gpu, mask, ok = res.sub_pod(
        jnp.int32(4000), jnp.int32(0), g(200, 1000, 1000, 500), pod
    )
    assert bool(ok)
    assert int(cpu) == 3000
    assert gpu.tolist()[:4] == [200, 1000, 1000, 200]
    assert mask.tolist()[:4] == [False, False, False, True]


def test_sub_whole_gpus_tie_by_index():
    pod = make_pod(cpu=0, gpu_milli=1000, gpu_num=2)
    _, _, gpu, mask, ok = res.sub_pod(
        jnp.int32(1000), jnp.int32(0), g(1000, 1000, 1000, 1000), pod
    )
    assert bool(ok)
    assert mask.tolist()[:4] == [True, True, False, False]
    assert gpu.tolist()[:4] == [0, 0, 1000, 1000]


def test_sub_infeasible():
    pod = make_pod(cpu=0, gpu_milli=1000, gpu_num=3)
    *_, ok = res.sub_pod(jnp.int32(1000), jnp.int32(0), g(1000, 500, 1000), pod)
    assert not bool(ok)
    pod = make_pod(cpu=9999, gpu_milli=0, gpu_num=0)
    *_, ok = res.sub_pod(jnp.int32(1000), jnp.int32(0), g(1000), pod)
    assert not bool(ok)


def test_add_inverts_sub():
    pod = make_pod(cpu=2000, mem=100, gpu_milli=450, gpu_num=1)
    cpu0, mem0, gpu0 = jnp.int32(8000), jnp.int32(500), g(700, 1000, 250, 0)
    cpu1, mem1, gpu1, mask, ok = res.sub_pod(cpu0, mem0, gpu0, pod)
    assert bool(ok)
    cpu2, mem2, gpu2 = res.add_pod(cpu1, mem1, gpu1, pod, mask)
    assert int(cpu2) == 8000 and int(mem2) == 500
    assert gpu2.tolist() == gpu0.tolist()


def test_can_host_and_allocate():
    gl = g(200, 1000, 1000, 500)
    assert bool(res.can_host_on_gpu(gl, jnp.int32(500), jnp.int32(3)))
    assert not bool(res.can_host_on_gpu(gl, jnp.int32(500), jnp.int32(4)))
    # two-pointer packs multiple sub-GPU units on one device:
    # floor-units = [0, 2, 2, 1] at 500m → 5 units
    assert bool(res.can_allocate(gl, jnp.int32(500), jnp.int32(5)))
    assert not bool(res.can_allocate(gl, jnp.int32(500), jnp.int32(6)))


def test_allocate_two_pointer_counts():
    take, ok = res.allocate_two_pointer(g(200, 1000, 1000, 500), jnp.int32(500), jnp.int32(3))
    assert bool(ok)
    assert take.tolist()[:4] == [0, 2, 1, 0]


def test_allocate_exclusive_first_free():
    mask = res.allocate_exclusive(g(500, 1000, 200, 1000, 1000), jnp.int32(2000))
    assert mask.tolist()[:5] == [False, True, False, True, False]
    none = res.allocate_exclusive(g(500, 1000), jnp.int32(2000))
    assert not bool(none.any())


def test_share_best_worst_random():
    gl = g(200, 1000, 1000, 500)
    assert int(res.allocate_share_best(gl, jnp.int32(300))) == 3
    assert int(res.allocate_share_worst(gl, jnp.int32(300))) == 1
    assert int(res.allocate_share_best(gl, jnp.int32(2000))) == -1
    dev = res.allocate_share_random(gl, jnp.int32(300), jax.random.PRNGKey(0))
    assert int(dev) in (1, 2, 3)


def test_accessibility():
    assert bool(res.is_accessible(jnp.int32(5), jnp.int32(0)))  # no constraint
    assert bool(res.is_accessible(jnp.int32(5), jnp.int32(1 << 5)))
    assert not bool(res.is_accessible(jnp.int32(4), jnp.int32(1 << 5)))
    assert not bool(res.is_accessible(jnp.int32(-1), jnp.int32(1 << 5)))
    assert bool(res.is_accessible(jnp.int32(-1), jnp.int32(0)))
