"""Policy-kernel tests. Golden values ported from the reference's
plugin/gpu_packing_score_test.go; other policies pinned by hand-computed
cases following the formulas in SURVEY.md §2.5."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusim.constants import GPU_MODEL_IDS, MILLI
from tpusim.policies import ScoreContext, jit_policy, make_policy
from tpusim.policies.dotprod import make_dotprod
from tpusim.types import NodeState, make_node_state, make_pod, make_typical_pods


def mk_state(gpu_lefts, cpu_left=1000, cpu_cap=96000, mem=262144, gpu_type="1080"):
    """One-node state with explicit per-device gpu_left."""
    n_dev = len(gpu_lefts)
    st = make_node_state(
        cpu_cap=[cpu_cap],
        mem_cap=[mem],
        gpu_cnt=[n_dev],
        gpu_type=[GPU_MODEL_IDS[gpu_type]],
    )
    gl = np.zeros((1, 8), np.int32)
    gl[0, :n_dev] = gpu_lefts
    return st._replace(
        cpu_left=jnp.asarray([cpu_left], jnp.int32), gpu_left=jnp.asarray(gl)
    )


def ctx_for(state, tp=None):
    return ScoreContext(
        tp=tp,
        feasible=jnp.ones(state.num_nodes, bool),
        rng=jax.random.PRNGKey(0),
    )


class TestPackingGolden:
    """Ported from gpu_packing_score_test.go."""

    def score(self, gpu_lefts, milli, num):
        st = mk_state(gpu_lefts)
        pod = make_pod(cpu=100, gpu_milli=milli, gpu_num=num)
        fn = make_policy("GpuPackingScore")
        return int(jit_policy(fn)(st, pod, ctx_for(st)).raw_scores[0])

    def test_case2_dip_into_free(self):
        assert self.score([200, 1000, 1000, 500], 1000, 2) == 48

    def test_case3_free_node_4gpu(self):
        assert self.score([1000, 1000, 1000, 1000], 1000, 2) == 29

    def test_case3_free_node_8gpu(self):
        assert self.score([1000] * 8, 1000, 2) == 25

    def test_case1_shared_only(self):
        assert self.score([200, 1000, 1000, 500], 200, 2) == 93


class TestBestFit:
    def test_formula(self):
        st = mk_state([500, 1000], cpu_left=31000)
        pod = make_pod(cpu=5000, gpu_milli=500, gpu_num=1)
        fn = make_policy("BestFitScore")
        # s = (31000-5000)/128000*0.5 + (1500-500)/8000*0.5 = 0.1015625+0.0625
        # score = floor((1-0.1640625)*100) = 83
        assert int(jit_policy(fn)(st, pod, ctx_for(st)).raw_scores[0]) == 83


class TestClustering:
    def test_quartiles(self):
        st = mk_state([500, 1000], cpu_left=31000)  # total_left=1500
        pack = 25 * (8000 - 1500) // 8000  # 20
        pod = make_pod(cpu=100, gpu_milli=500, gpu_num=1)  # share class 0
        fn = make_policy("GpuClusteringScore")

        # idle node, no affinities → (25, 50]
        assert int(jit_policy(fn)(st, pod, ctx_for(st)).raw_scores[0]) == 25 + pack
        # only same affinity → (75, 100]
        st2 = st._replace(aff_cnt=st.aff_cnt.at[0, 0].set(2))
        assert int(jit_policy(fn)(st2, pod, ctx_for(st2)).raw_scores[0]) == 75 + pack
        # multiple affinities incl pod's → (50, 75]
        st3 = st2._replace(aff_cnt=st2.aff_cnt.at[0, 1].set(1))
        assert int(jit_policy(fn)(st3, pod, ctx_for(st3)).raw_scores[0]) == 50 + pack
        # different affinity only → (0, 25]
        st4 = st._replace(aff_cnt=st.aff_cnt.at[0, 1].set(1))
        assert int(jit_policy(fn)(st4, pod, ctx_for(st4)).raw_scores[0]) == 0 + pack
        # no-gpu pod → 0
        cpu_pod = make_pod(cpu=100)
        assert int(jit_policy(fn)(st2, cpu_pod, ctx_for(st2)).raw_scores[0]) == 0


class TestFGD:
    def tp(self):
        return make_typical_pods(
            [(1000, 500, 1, 0, 0.5), (2000, 1000, 1, 0, 0.5)]
        )

    def test_prefers_frag_reducing_device(self):
        """Placing a 500m pod on the 500m-left device keeps the 1000m device
        usable by the 1-GPU typical pod — strictly better than breaking it."""
        st = mk_state([500, 1000], cpu_left=31000)
        pod = make_pod(cpu=1000, gpu_milli=500, gpu_num=1)
        fn = make_policy("FGDScore")
        res = jit_policy(fn)(st, pod, ctx_for(st, self.tp()))
        assert int(res.share_dev[0]) == 0

    def test_matches_manual_formula(self):
        from tpusim.ops.frag import node_frag_score

        st = mk_state([500, 1000], cpu_left=31000)
        tp = self.tp()
        pod = make_pod(cpu=1000, gpu_milli=500, gpu_num=1)
        fn = make_policy("FGDScore")
        got = int(jit_policy(fn)(st, pod, ctx_for(st, tp)).raw_scores[0])

        cur = float(
            node_frag_score(
                st.cpu_left[0], st.gpu_left[0], st.gpu_type[0], tp
            )
        )
        best = 0
        for d in range(2):
            gl = np.array(st.gpu_left[0])
            if gl[d] < 500:
                continue
            gl[d] -= 500
            new = float(
                node_frag_score(
                    st.cpu_left[0] - 1000, jnp.asarray(gl), st.gpu_type[0], tp
                )
            )
            s = int(np.floor(100.0 / (1.0 + np.exp(-(cur - new) / 1000.0))))
            best = max(best, s)
        assert got == best


class TestDotProduct:
    def test_merge_max_handcomputed(self):
        st = mk_state([500, 1000], cpu_left=31000)
        pod = make_pod(cpu=5000, gpu_milli=500, gpu_num=1)
        fn = make_dotprod("merge", "max")
        # nodeVec/max = [31000/128000, 1500/8000], podVec/max = [5000/128000, 500/8000]
        dot = ((31000 / 128000) * (5000 / 128000) + (1500 / 8000) * (500 / 8000)) / 2
        want = int(100 * (1 - dot))
        assert int(jit_policy(fn)(st, pod, ctx_for(st)).raw_scores[0]) == want

    def test_share_prefers_tight_device(self):
        st = mk_state([500, 1000], cpu_left=31000)
        pod = make_pod(cpu=5000, gpu_milli=400, gpu_num=1)
        fn = make_dotprod("share", "max")
        res = jit_policy(fn)(st, pod, ctx_for(st))
        # device slot 0 (500m shared) has smaller gpu dim → smaller dot →
        # higher score than the idle pool (1000m)
        assert int(res.share_dev[0]) == 0

    def test_infeasible_cpu_scores_zero(self):
        st = mk_state([500, 1000], cpu_left=100)
        pod = make_pod(cpu=5000, gpu_milli=400, gpu_num=1)
        for dim in ("merge", "share", "divide", "extend"):
            fn = make_dotprod(dim, "max")
            assert int(jit_policy(fn)(st, pod, ctx_for(st)).raw_scores[0]) == 0


class TestPWR:
    def test_share_picks_used_device(self):
        """V100M32: placing on an already-busy device adds no GPU power;
        waking an idle device costs full-minus-idle watts."""
        st = mk_state([500, 1000], gpu_type="V100M32")
        pod = make_pod(cpu=0, gpu_milli=400, gpu_num=1)
        fn = make_policy("PWRScore")
        res = jit_policy(fn)(st, pod, ctx_for(st))
        assert int(res.share_dev[0]) == 0
        assert int(res.raw_scores[0]) == 0  # no power delta on the busy device


class TestSimonAndRandom:
    def test_simon_share(self):
        # Simon scores against static ALLOCATABLE capacity, not free
        # resources (simon.go:59-64 reads node.Status.Allocatable, which the
        # fake cluster never decrements)
        st = mk_state([1000, 1000], cpu_left=10000, cpu_cap=10000, mem=100000)
        pod = make_pod(cpu=5000, mem=0, gpu_milli=0, gpu_num=0)
        fn = make_policy("Simon")
        # cpu share = 5000/(10000-5000) = 1.0 → score 100
        assert int(jit_policy(fn)(st, pod, ctx_for(st)).raw_scores[0]) == 100
        st2 = mk_state([1000, 1000], cpu_left=10000, cpu_cap=96000, mem=100000)
        # cpu share = 5000/91000, mem 0, gpu 0 → round(100 x 0.0549) = 5
        assert int(jit_policy(fn)(st2, pod, ctx_for(st2)).raw_scores[0]) == 5

    def test_random_single_winner(self):
        st = make_node_state(
            cpu_cap=[1000] * 4, mem_cap=[1000] * 4, gpu_cnt=[0] * 4,
            gpu_type=[-1] * 4,
        )
        fn = make_policy("RandomScore")
        scores = np.asarray(jit_policy(fn)(st, make_pod(cpu=1), ctx_for(st)).raw_scores)
        assert (scores == 100).sum() == 1 and (scores == 0).sum() == 3


def test_pwr_matches_direct_form():
    """The incremental PWR delta must equal re-running the full power model
    on every hypothetical, across random states incl. zero-milli share pods."""
    import jax

    from tpusim.constants import MAX_GPUS_PER_NODE
    from tpusim.ops.energy import node_power
    from tpusim.ops.resource import sub_pod
    from tpusim.policies.pwr import _pwr_node
    from tpusim.types import PodSpec

    def direct(row, pod):
        def power(cpu_left, gpu_left):
            c, g = node_power(
                cpu_left, row.cpu_cap, gpu_left, row.gpu_cnt, row.gpu_type,
                row.cpu_type,
            )
            return c + g

        old = power(row.cpu_left, row.gpu_left)

        def per_dev(d):
            return power(row.cpu_left - pod.cpu, row.gpu_left.at[d].add(-pod.gpu_milli))

        new_per_dev = jax.vmap(per_dev)(jnp.arange(MAX_GPUS_PER_NODE))
        fits = row.gpu_left >= pod.gpu_milli
        neg = jnp.int32(-(2**31) + 1)
        dev_scores = jnp.where(fits, (old - new_per_dev).astype(jnp.int32), neg)
        best = jnp.argmax(dev_scores)
        share = (jnp.where(fits.any(), dev_scores[best], neg),
                 jnp.where(fits.any(), best, -1))
        c2, _, g2, _, _ = sub_pod(row.cpu_left, row.mem_left, row.gpu_left, pod)
        whole = (old - power(c2, g2)).astype(jnp.int32)
        is_share = pod.is_gpu_share()
        return (jnp.where(is_share, share[0], whole),
                jnp.where(is_share, share[1], -1))

    rng = np.random.default_rng(77)
    from tpusim.types import make_node_state

    for trial in range(60):
        gcnt = int(rng.choice([0, 2, 4, 8]))
        st = make_node_state(
            cpu_cap=[int(rng.choice([32000, 96000]))],
            mem_cap=[262144],
            gpu_cnt=[gcnt],
            gpu_type=[int(rng.integers(0, 4)) if gcnt else -1],
            cpu_type=[int(rng.integers(0, 3))],
        )
        gl = np.zeros((1, 8), np.int32)
        gl[0, :gcnt] = rng.choice([0, 250, 500, 999, 1000], gcnt)
        st = st._replace(
            gpu_left=jnp.asarray(gl),
            cpu_left=jnp.asarray([int(rng.integers(0, 32000))], jnp.int32),
        )
        row = jax.tree.map(lambda a: a[0], st)
        pod = PodSpec(
            cpu=jnp.int32(int(rng.integers(0, 8000))),
            mem=jnp.int32(1024),
            gpu_milli=jnp.int32(int(rng.choice([0, 250, 500, 1000]))),
            gpu_num=jnp.int32(int(rng.choice([0, 1, 2]))),
            gpu_mask=jnp.int32(0),
            pinned=jnp.int32(-1),
        )
        a = jax.jit(_pwr_node)(row, pod)
        b = jax.jit(direct)(row, pod)
        assert int(a[0]) == int(b[0]) and int(a[1]) == int(b[1]), (
            trial, gl, pod, int(a[0]), int(b[0]), int(a[1]), int(b[1])
        )
