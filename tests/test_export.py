"""Snapshot export/import round-trips (ref: export.go +
inject_origin_workload_into_snapshot.py) and workload inflation."""

import csv

import numpy as np
import pytest

from tpusim.io.export import (
    export_node_snapshot_csv,
    export_pod_snapshot_csv,
    export_pod_snapshot_yaml,
    inject_snapshot_workload,
    load_pod_yaml,
)
from tpusim.io.trace import NodeRow, PodRow
from tpusim.sim.driver import Simulator, SimulatorConfig
from tpusim.sim.workload import inflation_pods


def _sim():
    nodes = [
        NodeRow("node-a", 32000, 262144, 4, "V100M16"),
        NodeRow("node-b", 64000, 262144, 8, "A100"),
        NodeRow("node-c", 96000, 262144, 0, ""),
    ]
    pods = [
        PodRow("p0", 4000, 1024, 1, 500, "V100M16", creation_time=1),
        PodRow("p1", 8000, 2048, 2, 1000, "", creation_time=2),
        PodRow("p2", 2000, 512, 0, 0, "", creation_time=3),
        PodRow("p3", 999000, 512, 0, 0, "", creation_time=4),  # unschedulable
    ]
    cfg = SimulatorConfig(policies=(("FGDScore", 1000),), report_per_event=False)
    sim = Simulator(nodes, cfg)
    sim.set_workload_pods(pods)
    sim.run()
    return sim


def test_pod_yaml_roundtrip(tmp_path):
    sim = _sim()
    path = str(tmp_path / "pod-snapshot.yaml")
    sim.export_pod_snapshot_yaml(path)
    back = load_pod_yaml(path)
    assert len(back) == 4
    by_name = {p.name: p for p in back}
    assert by_name["p0"].pinned_node in ("node-a", "node-b")
    assert by_name["p0"].gpu_milli == 500 and by_name["p0"].gpu_spec == "V100M16"
    assert by_name["p1"].num_gpu == 2
    assert by_name["p3"].unscheduled and by_name["p3"].pinned_node is None
    assert by_name["p2"].cpu_milli == 2000 and by_name["p2"].memory_mib == 512


def test_resume_rebinds_identically(tmp_path):
    sim = _sim()
    path = str(tmp_path / "pod-snapshot.yaml")
    sim.export_pod_snapshot_yaml(path)
    placed0 = {p.name: int(n) for p, n in zip(sim.last_result.pods, sim.last_result.placed_node)}

    back = load_pod_yaml(path)
    injected = inject_snapshot_workload(back, snapshot_id=1)
    sim2 = Simulator(sim.nodes, sim.cfg)
    sim2.set_workload_pods(injected)
    res2 = sim2.run()
    for p, n in zip(res2.pods, res2.placed_node):
        orig = p.name.rsplit("-ss", 1)[0]
        assert int(n) == placed0[orig], f"{p.name} rebound to {n} != {placed0[orig]}"
    # the annotated-unscheduled pod is skipped, not rescheduled
    # (simulator.go:391-399)
    reasons = {u.pod.name: u.reason for u in res2.unscheduled_pods}
    assert reasons.get("p3-ss1") == "pod-unscheduled annotation"


def test_pin_to_unknown_node_is_unschedulable():
    sim = _sim()
    pods = [PodRow("pinx", 1000, 128, 0, 0, "", pinned_node="no-such-node")]
    sim3 = Simulator(sim.nodes, sim.cfg)
    sim3.set_workload_pods(pods)
    res = sim3.run()
    assert int(res.placed_node[0]) == -1
    assert len(res.unscheduled_pods) == 1


def test_node_csv_schema(tmp_path):
    sim = _sim()
    path = str(tmp_path / "node-snapshot.csv")
    sim.export_node_snapshot_csv(path)
    with open(path) as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 3
    assert "gpu_milli_left_0" in rows[0] and "gpu_milli_left_7" in rows[0]
    total = sum(int(r["gpu_milli_left"]) for r in rows)
    s = sim.last_result.state
    assert total == int(np.asarray(s.gpu_left).sum())
    # schema matches the input-trace convention (data/README.md)
    assert rows[0]["name"] == "node-a" and rows[0]["model"] == "V100M16"


def test_pod_csv_schema(tmp_path):
    sim = _sim()
    path = str(tmp_path / "pod-snapshot.csv")
    sim.export_pod_snapshot_csv(path)
    with open(path) as f:
        rows = list(csv.DictReader(f))
    by_name = {r["pod"]: r for r in rows}
    assert by_name["p0"]["gpu_milli"] == "500"
    assert by_name["p0"]["gpu_mem_ratio"] == "50"
    assert by_name["p3"]["ip"] == ""  # unscheduled → no node


def test_inflation_breaks_at_capacity():
    rng = np.random.default_rng(0)
    workload = [PodRow(f"p{i}", 1000, 0, 1, 1000, "") for i in range(10)]
    # cluster gpu capacity 12000 milli, workload uses 10000 → room for 2 clones
    out = inflation_pods(workload, 2.0, rng, 10**9, 12000, 10000, 10000)
    assert len(out) == 2
    assert all(p.name.endswith(f"-clone-{i}") for i, p in enumerate(out))


def test_driver_inflation_restores_state():
    sim = _sim()
    sim.cfg.inflation_ratio = 1.5
    before = np.asarray(sim.last_result.state.cpu_left).copy()
    sim.run_workload_inflation_evaluation("ScheduleInflation")
    after = np.asarray(sim.last_result.state.cpu_left)
    np.testing.assert_array_equal(before, after)
