"""The mesh product path (SimulatorConfig.mesh / customConfig.mesh /
run.py --mesh): an end-to-end experiment sharded over the virtual 8-device
mesh must write analysis CSVs byte-identical to the single-device run —
sharding is an execution detail, not semantics (round-3/4 review item 4:
the engine existed but had no product path)."""

import sys
from pathlib import Path

import jax
import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[1]


def _load_runner():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "exp_run_mesh", REPO / "experiments" / "run.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_mesh_experiment_csvs_identical(tmp_path):
    from tests.test_experiments import _write_tiny_trace  # reuse fixture

    run = _load_runner()
    node_csv, pod_csv = _write_tiny_trace(tmp_path)
    outs = {}
    for label, extra in (("single", []), ("mesh", ["--mesh", "8"])):
        outdir = tmp_path / label
        run.run_experiment(run.get_args(
            ["-d", str(outdir), "-f", str(pod_csv), "--node-trace",
             str(node_csv), "-FGD", "1000", "-gpusel", "FGDScore", *extra]
        ))
        outs[label] = outdir
    files = sorted(
        p.name for p in outs["single"].iterdir() if p.name.startswith("analysis")
    )
    assert files
    for name in files:
        a = (outs["single"] / name).read_bytes()
        b = (outs["mesh"] / name).read_bytes()
        assert a == b, f"{name} differs between single-device and mesh runs"
    # the log names the engine (diagnosability), otherwise line-for-line
    la = (outs["single"] / "simon.log").read_text().splitlines()
    lb = (outs["mesh"] / "simon.log").read_text().splitlines()
    diff = [i for i, (x, y) in enumerate(zip(la, lb)) if x != y]
    assert all("[Engine]" in la[i] for i in diff)
    assert any("shard_map (mesh=8)" in lb[i] for i in diff)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_mesh_knob_via_simon_cr(tmp_path):
    """customConfig.mesh reaches the applier path."""
    import yaml

    from tpusim.apply import Applier, ApplyOptions

    cc = {
        "apiVersion": "simon/v1alpha1",
        "kind": "Config",
        "metadata": {"name": "mesh-test"},
        "spec": {
            "cluster": {"customConfig": "example/test-cluster"},
            "customConfig": {"mesh": 8},
        },
    }
    p = tmp_path / "cc.yaml"
    p.write_text(yaml.dump(cc))
    import io

    out = io.StringIO()
    applier = Applier(
        ApplyOptions(
            simon_config=str(p),
            default_scheduler_config=str(
                REPO / "example/test-scheduler-config.yaml"
            ),
            base_dir=str(REPO),
        )
    )
    result = applier.run(out=out)
    assert not result.unscheduled_pods
    assert "shard_map (mesh=8)" in out.getvalue()


def test_mesh_validation():
    from tpusim.io.trace import NodeRow
    from tpusim.sim.driver import Simulator, SimulatorConfig

    nodes = [NodeRow("n0", 8000, 16384, 2, "V100M16")]
    with pytest.raises(ValueError, match="devices"):
        Simulator(nodes, SimulatorConfig(mesh=4096))
    with pytest.raises(ValueError, match="random"):
        Simulator(
            nodes,
            SimulatorConfig(
                policies=(("RandomScore", 1000),), mesh=min(8, len(jax.devices()))
            ),
        )
