"""open-local storage extension: parsing, PVC synthesis, VG occupancy caps,
and the Node Local Storage report table (ref: pkg/utils/utils.go:555-668,
pkg/apply/apply.go:440-631)."""

import json
import os

import numpy as np
import pytest

from tpusim.io.storage import (
    cluster_vg_totals,
    match_local_storage_files,
    parse_node_storage,
    parse_pod_storage,
    pod_local_pvcs,
)
from tpusim.io.trace import NodeRow

NODE_STORAGE = {
    "vgs": [{"name": "share", "capacity": 500 * 1024**3, "requested": 100 * 1024**3}],
    "devices": [
        {"device": "/dev/vdb", "capacity": 1024**4, "mediaType": "HDD", "isAllocated": True}
    ],
}

POD_STORAGE = {
    "volumes": [
        {"size": "10737418240", "kind": "LVM", "scName": "open-local-lvm"},
        {"size": "1099511627776", "kind": "HDD", "scName": "open-local-device-hdd"},
        {"size": "42", "kind": "NAS", "scName": "whatever"},  # unsupported → skipped
    ]
}


def test_parse_node_storage():
    st = parse_node_storage(json.dumps(NODE_STORAGE))
    assert st.vgs[0].name == "share"
    assert st.vgs[0].capacity == 500 * 1024**3
    assert st.vgs[0].requested == 100 * 1024**3
    assert st.devices[0].media_type == "HDD" and st.devices[0].is_allocated
    assert parse_node_storage(None) is None


def test_parse_pod_storage_and_pvcs():
    vols = parse_pod_storage(json.dumps(POD_STORAGE))
    assert len(vols) == 3 and vols[0].size == 10737418240
    lvm, dev = pod_local_pvcs("p0", "ns", vols)
    assert [p.name for p in lvm] == ["pvc-p0-0"]
    assert [p.name for p in dev] == ["pvc-p0-1"]  # NAS volume skipped
    assert lvm[0].sc_name == "open-local-lvm"


def test_match_local_storage_files(tmp_path):
    (tmp_path / "node-a.json").write_text(json.dumps(NODE_STORAGE))
    (tmp_path / "other.json").write_text(json.dumps(NODE_STORAGE))
    (tmp_path / "bad.json").write_text("{nope")
    found = match_local_storage_files(["node-a", "node-b"], str(tmp_path))
    assert set(found) == {"node-a"}


def test_cluster_vg_totals():
    st = parse_node_storage(NODE_STORAGE)
    req, cap = cluster_vg_totals([st, None, st])
    assert req == 200 * 1024**3 and cap == 1000 * 1024**3


def test_node_storage_report_table():
    from tpusim.sim.report_tables import node_storage_table

    nodes = [
        NodeRow("n0", 1000, 1024, 0, local_storage=NODE_STORAGE),
        NodeRow("n1", 1000, 1024, 0),
    ]
    out = node_storage_table(nodes)
    assert "VG" in out and "share" in out and "500Gi" in out and "(20%)" in out
    assert "Device(HDD)" in out and "used" in out
    assert "n1" not in out


def test_yaml_ingest_storage_annotation(tmp_path):
    import yaml as pyyaml

    from tpusim.io.k8s_yaml import load_cluster_from_dir

    node = {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": "stor-node",
            "annotations": {"simon/node-local-storage": json.dumps(NODE_STORAGE)},
        },
        "status": {"allocatable": {"cpu": "4", "memory": "8Gi"}},
    }
    (tmp_path / "node.yaml").write_text(pyyaml.dump(node))
    # sidecar json for a second node
    node2 = dict(node, metadata={"name": "stor-node2"})
    (tmp_path / "node2.yaml").write_text(pyyaml.dump(node2))
    (tmp_path / "stor-node2.json").write_text(json.dumps(NODE_STORAGE))
    res = load_cluster_from_dir(str(tmp_path))
    by_name = {n.name: n for n in res.nodes}
    assert parse_node_storage(by_name["stor-node"].local_storage).vgs[0].name == "share"
    assert parse_node_storage(by_name["stor-node2"].local_storage).vgs[0].name == "share"


def test_maxvg_verdict(monkeypatch, tmp_path):
    """MaxVG percent cap fails the run when VG occupancy exceeds it
    (apply.go:617-623)."""
    from tpusim.apply import Applier

    class FakeState:
        cpu_cap = np.array([4000]); cpu_left = np.array([4000])
        mem_cap = np.array([8192]); mem_left = np.array([8192])

    class FakeResult:
        state = FakeState(); node_names = ["n0"]

    class FakeSim:
        nodes = [NodeRow("n0", 4000, 8192, 0, local_storage=NODE_STORAGE)]

    app = Applier.__new__(Applier)
    app.sim = FakeSim()
    monkeypatch.setenv("MaxVG", "10")  # VG occupancy is 20%
    ok, reason = app._satisfy_resource_setting(FakeResult())
    assert not ok and "vg" in reason
    monkeypatch.setenv("MaxVG", "50")
    ok, _ = app._satisfy_resource_setting(FakeResult())
    assert ok
    monkeypatch.setenv("MaxVG", "150")  # out of range → clamp to 100 → ok
    ok, _ = app._satisfy_resource_setting(FakeResult())
    assert ok
