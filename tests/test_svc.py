"""The queueing what-if replay service (tpusim.svc; ISSUE 7).

Pins the service contracts end-to-end:

  1. job validation + the grid expander (no device work);
  2. the digest vocabulary: deterministic, moves with every spec field
     and the trace content, identical jobs share — and the TABLE digest
     is tune-independent (the operand lift moved the per-pod type map
     from the table key to the run key);
  3. signed result persistence: round-trip, torn-file rejection
     (deleted + recomputed, never served), foreign-header rejection;
  4. batch formation: compatible jobs coalesce FIFO up to the lane
     width, incompatible jobs don't, full queues raise QueueFull and
     the HTTP plane answers 429 + Retry-After;
  5. POST-path bit-identity: every job's placements equal a standalone
     run with that weight vector/seed/tune factor baked into the
     config, duplicates answered from the digest cache;
  6. zero recompiles: two batches differing only in weights+tune share
     ONE compiled sweep executable (jit._cache_size() stable);
  7. per-job /progress (the heartbeat job-tag satellite) and the
     watch_dir TOCTOU fix.

The openb end-to-end acceptance (N concurrent jobs over real HTTP,
<= ceil(N/B) compiled sweeps, marginal cost bound) is slow-marked into
`make resume-smoke` — the tier-1 slice here stays on a tiny synthetic
cluster sharing one compiled family.
"""

import json
import os

import numpy as np
import pytest

from tpusim.io.trace import NodeRow, PodRow
from tpusim.sim.typical import TypicalPodsConfig
from tpusim.svc import jobs as svc_jobs
from tpusim.svc.api import JobService
from tpusim.svc.batcher import JobQueue, QueueFull
from tpusim.svc.worker import TraceRef, Worker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAM = [["FGDScore", 1000], ["BestFitScore", 500]]


def _mk_cluster(rng, n=16):
    return [
        NodeRow(f"n{i:03d}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], n))
    ]


def _mk_pods(rng, n=40):
    out = []
    for i in range(n):
        gpu = int(rng.choice([0, 1, 2]))
        milli = 1000 if gpu > 1 else int(rng.choice([0, 300, 500, 1000]))
        if gpu == 0:
            milli = 0
        out.append(
            PodRow(f"p{i:04d}", int(rng.choice([1000, 2000, 4000])), 2048,
                   gpu, milli)
        )
    return out


@pytest.fixture(scope="module")
def trace():
    rng = np.random.default_rng(3)
    nodes, pods = _mk_cluster(rng), _mk_pods(rng)
    return TraceRef(
        "default", nodes, pods, svc_jobs.trace_digest(nodes, pods)
    )


def _standalone(trace, weights, seed, tune):
    """A standalone baked-config run over the hosted trace — the
    bit-identity oracle for one job."""
    from tpusim.sim.driver import Simulator, SimulatorConfig

    sim = Simulator(trace.nodes, SimulatorConfig(
        policies=tuple((n, int(w)) for (n, _), w in zip(FAM, weights)),
        gpu_sel_method="best", seed=seed, report_per_event=False,
        tuning_ratio=tune, shuffle_pod=False,
    ))
    sim.set_workload_pods(trace.pods)
    return sim.run()


def _service(trace, tmp_path, lane_width=4, queue_size=16):
    """An in-process service stack with a SYNCHRONOUS worker (no thread):
    tests drive batch formation deterministically via drain()."""
    queue = JobQueue(maxsize=queue_size, lane_width=lane_width)
    worker = Worker(queue, {"default": trace}, str(tmp_path))
    service = JobService(queue, worker, {"default": trace}, str(tmp_path))
    return queue, worker, service


def _drain(queue, worker):
    batches = 0
    while True:
        batch = queue.next_batch(timeout=0)
        if not batch:
            return batches
        worker.run_batch(batch)
        batches += 1


def _post(service, doc):
    """Drive the real POST surface (MonitorServer routes here)."""
    return service.handle("POST", "/jobs", json.dumps(doc).encode())


def _body(resp):
    return json.loads(resp[2].decode())


# ---------------------------------------------------------------------------
# 1. validation + grid expansion (no device)
# ---------------------------------------------------------------------------


def test_validate_job():
    spec = svc_jobs.validate_job({})
    assert spec.policies == svc_jobs.DEFAULT_POLICIES
    assert spec.weights == (1000,)  # defaults to the family weights
    assert spec.engine == "auto" and spec.tune == 0.0

    spec = svc_jobs.validate_job({
        "policies": FAM, "weights": [7, 9], "seed": 5, "tune": 1.5,
        "gpu_sel": "FGDScore", "engine": "table",
    })
    assert spec.weights == (7, 9) and spec.tune == 1.5
    assert spec.family_key() == (
        "default", ("FGDScore", "BestFitScore"), "FGDScore", "max",
        "share", "table", False,
    )
    # fault jobs (ISSUE 10) batch separately; the ISSUE 12 lift made
    # the tune factor an operand for them too — no longer in the key
    spec_f = svc_jobs.validate_job({
        "policies": FAM, "tune": 1.5,
        "fault": {"mtbf_events": 5.0, "seed": 7},
    })
    assert spec_f.fault_config().mtbf_events == 5.0
    assert spec_f.family_key()[-1] is True
    spec_nf = svc_jobs.validate_job({"policies": FAM, "tune": 1.5})
    assert spec_f.family_key() != spec_nf.family_key()
    spec_f2 = svc_jobs.validate_job({
        "policies": FAM, "tune": 0.5,
        "fault": {"mtbf_events": 5.0, "seed": 7},
    })
    assert spec_f.family_key() == spec_f2.family_key()
    with pytest.raises(ValueError, match="unknown fault key"):
        svc_jobs.validate_job({"fault": {"mtbf": 5.0}})
    with pytest.raises(ValueError, match="fault needs"):
        svc_jobs.validate_job({"fault": {"seed": 3}})

    with pytest.raises(ValueError, match="unknown job key"):
        svc_jobs.validate_job({"wieghts": [1]})
    with pytest.raises(ValueError, match="unknown policy"):
        svc_jobs.validate_job({"policies": [["NoSuchScore", 1]]})
    with pytest.raises(ValueError, match="one integer per policy"):
        svc_jobs.validate_job({"policies": FAM, "weights": [1]})
    with pytest.raises(ValueError, match="engine must be one of"):
        svc_jobs.validate_job({"engine": "pallas"})
    with pytest.raises(ValueError, match="tune must be >= 0"):
        svc_jobs.validate_job({"tune": -1})
    with pytest.raises(ValueError, match="must be an integer"):
        svc_jobs.validate_job({"seed": "42"})
    # method typos must be 400s, not silently-default replays cached
    # under the typo'd digest (sim.step's gpu_sel dispatch has no
    # else-error — validation is the only fail-loudly point)
    with pytest.raises(ValueError, match="gpu_sel must be"):
        svc_jobs.validate_job({"gpu_sel": "bets"})
    with pytest.raises(ValueError, match="norm must be"):
        svc_jobs.validate_job({"norm": "maxx"})
    with pytest.raises(ValueError, match="dim_ext must be"):
        svc_jobs.validate_job({"dim_ext": "shared"})


def test_jobs_from_grid():
    docs = svc_jobs.jobs_from_grid({
        "weights": [[1000, 1], [2, 2000]], "seeds": [4, 5],
        "tunes": [0.0, 1.3], "policies": FAM, "gpu_sel": "FGDScore",
    })
    assert len(docs) == 2
    assert docs[1] == {
        "weights": [2, 2000], "seed": 5, "tune": 1.3, "policies": FAM,
        "gpu_sel": "FGDScore",
    }
    # bare rows + default family; full job docs pass through
    docs = svc_jobs.jobs_from_grid([[10], [20]])
    assert [d["weights"] for d in docs] == [[10], [20]]
    passthrough = [{"weights": [1], "seed": 9}]
    assert svc_jobs.jobs_from_grid({"jobs": passthrough}) == passthrough
    with pytest.raises(ValueError, match="no weight rows"):
        svc_jobs.jobs_from_grid([])
    with pytest.raises(ValueError, match="seeds has 1"):
        svc_jobs.jobs_from_grid({"weights": [[1], [2]], "seeds": [3]})
    # singular-key typos are loud, never silently-defaulted rows
    with pytest.raises(ValueError, match="unknown grid key.*seed"):
        svc_jobs.jobs_from_grid({"weights": [[1], [2]], "seed": 7})


def test_docs_from_payload_routing():
    """The `tpusim submit` shape router: a single job document carrying
    a FLAT `weights` vector (a JOB_KEYS field) must stay one job, not
    misroute into the grid expander."""
    single = {"policies": FAM, "weights": [1000, 500], "seed": 7}
    assert svc_jobs.docs_from_payload(single) == [single]
    # rows-of-lists -> grid; list-of-docs and {"jobs"} pass through
    assert [d["weights"] for d in
            svc_jobs.docs_from_payload({"weights": [[1], [2]]})] \
        == [[1], [2]]
    assert svc_jobs.docs_from_payload([[10], [20]])[1]["weights"] == [20]
    assert svc_jobs.docs_from_payload([single]) == [single]
    assert svc_jobs.docs_from_payload({"jobs": [single]}) == [single]


# ---------------------------------------------------------------------------
# 2. digest vocabulary
# ---------------------------------------------------------------------------


def test_job_digest_vocabulary():
    base = svc_jobs.validate_job({"policies": FAM, "seed": 42})
    d0 = svc_jobs.job_digest(base, "tracedigest")
    assert d0 == svc_jobs.job_digest(base, "tracedigest")  # deterministic
    for variant in (
        {"policies": FAM, "seed": 43},
        {"policies": FAM, "seed": 42, "weights": [999, 500]},
        {"policies": FAM, "seed": 42, "tune": 0.1},
        {"policies": FAM, "seed": 42, "engine": "table"},
    ):
        assert svc_jobs.job_digest(
            svc_jobs.validate_job(variant), "tracedigest"
        ) != d0, variant
    # the hosted trace's CONTENT participates
    assert svc_jobs.job_digest(base, "othertrace") != d0


def test_tables_digest_tune_independent(trace):
    """The operand lift's digest move: traces differing only in tune
    factor (same distinct type set, different per-pod type_id) share ONE
    table-cache entry — while the run digest still moves."""
    import jax

    from tpusim.io.trace import build_events, pods_to_specs
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.table_engine import build_pod_types

    sim = Simulator(trace.nodes, SimulatorConfig(
        policies=(("FGDScore", 1000),), report_per_event=False,
        shuffle_pod=False,
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
    ))
    sim.set_workload_pods(trace.pods)
    sim.set_typical_pods()

    def digests(tune):
        pods = sim.prepare_pods(tuning_ratio=tune)
        specs = pods_to_specs(pods, sim.node_index)
        ev_kind, ev_pod = build_events(pods)
        types = build_pod_types(specs)
        tbl = sim._tables_digest(sim.init_state, types)
        run = sim._run_digest(
            sim.init_state, specs, np.asarray(ev_kind),
            np.asarray(ev_pod), np.asarray(jax.random.PRNGKey(42)),
            np.asarray(sim.rank),
        )
        return tbl, run

    tbl_a, run_a = digests(0.0)
    tbl_b, run_b = digests(1.5)
    assert tbl_a == tbl_b  # tune factor left the table key...
    assert run_a != run_b  # ...and lives in the run key (specs/events)


# ---------------------------------------------------------------------------
# 3. signed result persistence
# ---------------------------------------------------------------------------


def test_signed_result_roundtrip(tmp_path):
    art = str(tmp_path)
    result = {"job": "d" * 64, "placed": 12, "weights": [7, 9],
              "gpu_alloc_pct": 33.25}
    path = svc_jobs.write_result(art, "d" * 64, result)
    assert svc_jobs.find_result(art, "d" * 64) == result

    # torn file: fails the payload digest, gets deleted, reads as a miss
    with open(path) as f:
        lines = f.read().splitlines()
    with open(path, "w") as f:
        f.write(lines[0] + "\n")
        f.write(lines[1].replace("12", "13") + "\n")
    assert svc_jobs.find_result(art, "d" * 64) is None
    assert not os.path.exists(path)

    # foreign header (digest-valid but for another job) never matches
    svc_jobs.write_result(art, "e" * 64, dict(result, job="x"))
    path_e = svc_jobs.result_path(art, "e" * 64)
    os.replace(path_e, svc_jobs.result_path(art, "f" * 64))
    assert svc_jobs.find_result(art, "f" * 64) is None


# ---------------------------------------------------------------------------
# 4. batch formation + backpressure (no device)
# ---------------------------------------------------------------------------


def test_batch_formation_and_queue_full():
    q = JobQueue(maxsize=4, lane_width=3)
    fam_a = svc_jobs.validate_job({"policies": FAM})
    fam_b = svc_jobs.validate_job({"policies": FAM, "gpu_sel": "FGDScore"})
    a1 = q.submit(fam_a, "a1")
    b1 = q.submit(fam_b, "b1")
    a2 = q.submit(svc_jobs.validate_job(
        {"policies": FAM, "weights": [1, 2], "tune": 2.0}), "a2")
    a3 = q.submit(svc_jobs.validate_job(
        {"policies": FAM, "seed": 9}), "a3")
    with pytest.raises(QueueFull) as exc:
        q.submit(svc_jobs.validate_job({"policies": FAM, "seed": 10}), "a4")
    assert exc.value.retry_after_s >= 1
    assert q.stats()["rejected"] == 1

    # dedup: a known digest re-submits to the SAME job, no queue slot
    assert q.submit(fam_a, "a1") is a1
    assert q.depth() == 4

    # batch 1: the a-family coalesces FIFO (a1, a2, a3 — b1 skipped,
    # weights/tune differences do NOT split the family), capped at 3
    batch = q.next_batch(timeout=0)
    assert [j.id for j in batch] == [a1.id, a2.id, a3.id]
    assert [j.lane for j in batch] == [0, 1, 2]
    assert all(j.status == "batched" for j in batch)
    # batch 2: the incompatible job rides its own (singleton) batch
    assert [j.id for j in q.next_batch(timeout=0)] == [b1.id]
    assert q.next_batch(timeout=0) == []

    # a failed job releases its digest for re-submission
    q.mark_failed(a1, "boom")
    retry = q.submit(fam_a, "a1")
    assert retry is not a1 and retry.status == "queued"


# ---------------------------------------------------------------------------
# 5./6. POST-path bit-identity, dedup, 429, zero recompiles
# ---------------------------------------------------------------------------


@pytest.mark.slow  # tier-1 trim, ISSUE 16: rides resume-smoke
def test_post_path_lane_vs_standalone(trace, tmp_path):
    """The marquee contract: results served through the POST path are
    bit-identical to standalone baked-config runs — across weight,
    seed, AND tune-factor variants batched onto one sweep — duplicates
    come from the digest cache, and a second batch differing only in
    weights+tune adds no compiled executable."""
    from tpusim.sim.driver import _sweep_engine_multi

    queue, worker, service = _service(trace, tmp_path)
    # two tune-1.3 jobs deliberately share their tuned trace shape (and
    # the tune-0 job the base shape): the tier-1 slice pays one
    # standalone-engine compile per DISTINCT shape, not per job
    docs = [
        {"policies": FAM, "weights": [1000, 500], "seed": 42},
        {"policies": FAM, "weights": [100, 2000], "seed": 43, "tune": 1.3},
        {"policies": FAM, "weights": [1000, 500], "seed": 42},  # duplicate
        {"policies": FAM, "weights": [7, 900], "seed": 44, "tune": 1.3},
    ]
    resp = _post(service, {"jobs": docs})
    assert resp[0] == 202, resp
    accepted = _body(resp)["jobs"]
    assert accepted[0]["id"] == accepted[2]["id"]  # in-queue dedup
    assert queue.stats()["dedup_hits"] == 1
    assert _drain(queue, worker) == 1  # one compatible batch

    # (the duplicate needs no oracle of its own — it IS job 0's record,
    # pinned by the id equality above)
    for doc in (docs[0], docs[1], docs[3]):
        job_id = _body(_post(service, doc))["id"]
        code, _, body = service.handle(
            "GET", f"/jobs/{job_id}/result", b"")[:3]
        assert code == 200
        got = json.loads(body.decode())
        res = _standalone(
            trace, doc["weights"], doc.get("seed", 42), doc.get("tune", 0.0)
        )
        np.testing.assert_array_equal(
            np.asarray(got["placed_node"]), np.asarray(res.placed_node)
        )
        assert got["failed"] == len(res.unscheduled_pods)
        assert got["events"] == res.events
    # those re-submissions were all answered from the digest cache —
    # nothing new to drain, the device was never touched
    assert queue.depth() == 0 and worker.batches_run == 1

    # zero recompiles: a second batch differing only in weights+tune
    # must not grow the jitted sweep wrapper's executable cache (counts
    # are read RELATIVE to the first batch — the wrapper is process-
    # global, so sibling tests may have compiled other shapes into it)
    # the service lane runs report_per_event=False, so the dispatch
    # resolves the STREAM-DONATING twin (ISSUE 15) — ask for that one
    fn = _sweep_engine_multi(
        worker._sims[list(worker._sims)[0]]._table_fn.engine.replay,
        table=True, donate_streams=True,
    )
    before = fn._cache_size()
    _post(service, {"policies": FAM, "weights": [555, 111], "tune": 1.1,
                    "seed": 7})
    assert _drain(queue, worker) == 1
    assert fn._cache_size() == before
    assert worker.sweep_executables() == fn._cache_size()

    # GET surfaces: status doc, /queue stats, unknown id
    jid = _body(_post(service, docs[0]))["id"]
    code, _, body = service.handle("GET", f"/jobs/{jid}", b"")[:3]
    assert code == 200 and json.loads(body.decode())["status"] == "done"
    code, _, body = service.handle("GET", "/queue", b"")[:3]
    stats = json.loads(body.decode())
    assert code == 200 and stats["sweep_executables"] == before
    assert stats["batches_run"] == 2
    assert service.handle("GET", "/jobs/nope", b"")[0] == 404
    # a result file landed per distinct job, signed
    digests = {j.digest for j in queue._jobs.values()}
    for d in digests:
        assert svc_jobs.find_result(str(tmp_path), d) is not None


def test_http_429_retry_after(trace, tmp_path):
    queue, worker, service = _service(trace, tmp_path, queue_size=2)
    for i in range(2):
        assert _post(service, {"policies": FAM, "seed": i})[0] == 202
    resp = _post(service, {"policies": FAM, "seed": 99})
    code, ctype, body, headers = resp
    assert code == 429
    assert int(headers["Retry-After"]) >= 1
    doc = json.loads(body.decode())
    assert doc["retry_after_s"] == int(headers["Retry-After"])
    # an in-flight (not yet done) job answers /result with 409
    jid = _body(_post(service, {"policies": FAM, "seed": 0}))["id"]
    assert service.handle("GET", f"/jobs/{jid}/result", b"")[0] == 409
    # malformed docs are 400 with the validation message
    resp = _post(service, {"wieghts": [1]})
    assert resp[0] == 400 and "unknown job key" in _body(resp)["error"]
    assert _post(service, {"trace": "nope"})[0] == 400


# ---------------------------------------------------------------------------
# 7. per-job progress + watch_dir TOCTOU
# ---------------------------------------------------------------------------


def test_heartbeat_job_tag_routes_progress():
    from tpusim.obs import heartbeat
    from tpusim.obs.server import MonitorServer

    srv = MonitorServer(":0")  # never started: write surface only
    srv.attach_heartbeat()
    try:
        seen = []
        listener = seen.append
        heartbeat.add_listener(listener)
        try:
            heartbeat.configure(100, "replay", sink=lambda line: None,
                                job="j00001-abc")
            heartbeat.tick(50)
            heartbeat.complete(100)
        finally:
            heartbeat.remove_listener(listener)
        assert seen and all(i["job"] == "j00001-abc" for i in seen)
        # tagged ticks land under /progress's jobs map, not the flat keys
        assert "events_done" not in srv._progress
        entry = srv._progress["jobs"]["j00001-abc"]
        assert entry["events_total"] == 100
        assert srv._progress["job"] == "j00001-abc"

        # untagged ticks keep the flat single-run behavior
        heartbeat.configure(10, "replay", sink=lambda line: None)
        heartbeat.complete(10)
        assert srv._progress["events_done"] == 10
    finally:
        srv.stop()
        heartbeat.configure(0, sink=None)


def test_progress_jobs_map_bounded():
    from tpusim.obs.server import MonitorServer

    srv = MonitorServer(":0")
    for i in range(srv.MAX_JOB_PROGRESS + 9):
        srv.publish_job_progress(f"j{i:04d}", {"phase": "done"})
    jobs = srv._progress["jobs"]
    assert len(jobs) == srv.MAX_JOB_PROGRESS
    assert "j0000" not in jobs  # oldest aged out FIFO


def test_watch_dir_survives_vanishing_files(tmp_path, monkeypatch):
    from tpusim.obs import server as obs_server

    keep = tmp_path / "keep.jsonl"
    keep.write_text('{"deterministic": {}, "timing": {}}\n')
    gone = tmp_path / "gone.jsonl"
    gone.write_text("{}\n")

    real_getmtime = os.path.getmtime

    def racy_getmtime(path):
        # the TOCTOU race: the file vanishes between listdir and stat
        if os.path.basename(path) == "gone.jsonl":
            os.unlink(path)
            raise FileNotFoundError(path)
        return real_getmtime(path)

    monkeypatch.setattr(
        obs_server.os.path, "getmtime", racy_getmtime
    )
    record, progress = obs_server.watch_dir(str(tmp_path))
    assert record is not None  # the surviving record is still served
    assert progress["record_file"] == "keep.jsonl"


# ---------------------------------------------------------------------------
# openb end-to-end acceptance (slow; `make resume-smoke` / `make svc-smoke`)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_openb_service_acceptance(tmp_path):
    """ISSUE 7 acceptance on the openb prefix, over real HTTP: N jobs
    POSTed concurrently are served from <= ceil(N/B) compiled sweeps
    with zero recompiles after the first batch, every result
    bit-identical to a standalone run with that weight vector/seed/tune
    baked, duplicates answered from the digest cache without touching
    the device, and the marginal per-job wall beating a standalone warm
    replay outright on CPU (<= 1/5 of it off-CPU)."""
    import time

    import jax

    from tpusim.io.trace import load_node_csv, load_pod_csv
    from tpusim.svc import start_job_server
    from tpusim.svc.client import _request, submit_and_wait

    nodes = load_node_csv(
        os.path.join(REPO, "data/csv/openb_node_list_gpu_node.csv")
    )
    pods = load_pod_csv(
        os.path.join(REPO, "data/csv/openb_pod_list_default.csv")
    )[:400]
    trace = TraceRef(
        "default", nodes, pods, svc_jobs.trace_digest(nodes, pods)
    )
    n_jobs, lane_width = 6, 4
    srv, service, worker = start_job_server(
        str(tmp_path), {"default": trace}, listen=":0",
        lane_width=lane_width, queue_size=32,
    )
    try:
        fam = [["FGDScore", 1000], ["BestFitScore", 500]]
        docs = [
            {"policies": fam, "weights": [1000 - 37 * i, 100 + 60 * i],
             "seed": 42 + (i % 2), "tune": [0.0, 0.2][i % 2]}
            for i in range(n_jobs)
        ]
        results = submit_and_wait(srv.url, docs, timeout=600)
        _, _, q = _request(srv.url + "/queue")
        # <= ceil(N/B) compiled sweeps; executables read relative (the
        # jitted wrapper is process-global — sibling tests may have
        # compiled other shapes into it before this one ran)
        assert q["batches_run"] <= -(-n_jobs // lane_width)
        execs0 = q["sweep_executables"]

        # bit-identity of every job against its standalone baked run
        for doc, got in zip(docs, results):
            from tpusim.sim.driver import Simulator, SimulatorConfig

            sim = Simulator(nodes, SimulatorConfig(
                policies=(("FGDScore", doc["weights"][0]),
                          ("BestFitScore", doc["weights"][1])),
                gpu_sel_method="best", seed=doc["seed"],
                report_per_event=False, tuning_ratio=doc["tune"],
                shuffle_pod=False,
            ))
            sim.set_workload_pods(pods)
            res = sim.run()
            np.testing.assert_array_equal(
                np.asarray(got["placed_node"]), np.asarray(res.placed_node)
            )
            assert got["failed"] == len(res.unscheduled_pods)

        # duplicates: the whole wave again — zero new batches, the
        # device untouched, results identical
        batches_before = q["batches_run"]
        dup = submit_and_wait(srv.url, docs, timeout=60)
        _, _, q2 = _request(srv.url + "/queue")
        assert q2["batches_run"] == batches_before
        # zero recompiles after the first batch: every batch of the N-job
        # wave and the dup wave ran on the executables of batch 1
        assert q2["sweep_executables"] == execs0, (q, q2)
        assert [d["placements_sha256"] for d in dup] == [
            d["placements_sha256"] for d in results
        ]

        # marginal per-job cost through the POST path: the slope between
        # a full fresh wave and a single fresh job — both warm and both
        # padded to the SAME lane width/shapes by the service, so the
        # slope isolates what one EXTRA job costs once a batch exists —
        # against a warm single-lane replay at the same padded shapes
        # (the worker's sticky floors; this B=1 call compiles its own
        # vmap shape, which is why it comes after the stability checks)
        from tpusim.sim.driver import schedule_pods_sweep_multi
        from tpusim.svc.client import submit_jobs, wait_jobs

        sim = worker._sims[list(worker._sims)[0]]
        hw_p, hw_e = worker._shape_hw[list(worker._shape_hw)[0]]
        trace_pods = sim.prepare_pods()

        def standalone_warm():
            t0 = time.perf_counter()
            schedule_pods_sweep_multi(
                sim, [trace_pods], np.asarray([[1000, 500]], np.int32),
                seeds=[42], min_pods=hw_p, min_events=hw_e,
            )
            return time.perf_counter() - t0

        standalone_warm()  # compile the B=1 vmap shape
        sw = min(standalone_warm() for _ in range(2))

        def fresh(i):  # every wave needs undedup'd weights
            return {"policies": fam, "weights": [400 + i, 800 - i],
                    "seed": 42}

        def wave_wall(wave):
            t0 = time.perf_counter()
            ids = [a["id"] for a in submit_jobs(srv.url, wave)]
            wait_jobs(srv.url, ids, timeout=600, poll_s=0.02)
            return time.perf_counter() - t0

        wave_wall([fresh(0)])  # warm the HTTP + dispatch path
        wall_b = min(
            wave_wall([fresh(10 * r + j) for j in range(1, lane_width + 1)])
            for r in range(2)
        )
        wall_1 = min(wave_wall([fresh(100 + r)]) for r in range(2))
        marginal = max(wall_b - wall_1, 0.0) / (lane_width - 1)
        bound = 0.2 if jax.default_backend() != "cpu" else 1.0
        assert marginal <= bound * sw, (marginal, wall_b, wall_1, sw)
        # and a whole fresh B-job batch beats B standalone warm replays
        assert wall_b < lane_width * sw, (wall_b, sw)
    finally:
        worker.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# 8. ISSUE 9 satellites: singular grid keys, shared poll backoff,
#    nonzero submit exit on failed jobs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("singular,plural", [
    ("weight", "weights"), ("seed", "seeds"), ("tune", "tunes"),
])
def test_grid_singular_keys_rejected(singular, plural):
    """Every singular form of a per-row vector fails LOUDLY, naming its
    plural — a typo'd grid must never run rows at the defaults."""
    with pytest.raises(ValueError) as err:
        svc_jobs.jobs_from_grid(
            {"weights": [[1], [2]], singular: 7}
            if singular != "weight" else
            {"weights": [[1], [2]], "weight": [3]}
        )
    msg = str(err.value)
    assert f'"{singular}"' in msg and f'"{plural}"' in msg


def test_wait_jobs_uses_shared_backoff(monkeypatch):
    """The poll loop sleeps the kube_client capped-exponential-with-
    jitter schedule (ONE shared utility): idle rounds escalate the
    attempt count, any job reaching terminal resets it."""
    from tpusim.svc import client

    # job j1 turns done on the 2nd poll, j2 on the 5th
    polls = {"n": 0}

    def fake_request(url, data=None, timeout=30.0):
        jid = url.rsplit("/", 1)[-1]
        if jid == "j1":
            status = "done" if polls["n"] >= 1 else "running"
        else:
            status = "done" if polls["n"] >= 4 else "running"
        return 200, {}, {"id": jid, "status": status}

    attempts = []

    def fake_delay(attempt, retry_after=None):
        attempts.append(attempt)
        return 0.0

    slept = []
    monkeypatch.setattr(client, "_request", fake_request)
    monkeypatch.setattr(client, "_retry_delay_s", fake_delay)

    def fake_sleep(s):
        slept.append(s)
        polls["n"] += 1

    monkeypatch.setattr(client.time, "sleep", fake_sleep)
    final = client.wait_jobs("http://x", ["j1", "j2"], timeout=60)
    assert [d["status"] for d in final] == ["done", "done"]
    # round 0: both running -> attempt 1; round 1: j1 done (progress) ->
    # reset to 1; rounds 2..: idle polls escalate 2, 3
    assert attempts == [1, 1, 2, 3]


def test_wait_jobs_poll_cap(monkeypatch):
    """poll_s > 0 caps the shared-backoff delay (the fast-test knob)."""
    from tpusim.svc import client

    calls = {"n": 0}

    def fake_request(url, data=None, timeout=30.0):
        calls["n"] += 1
        status = "done" if calls["n"] >= 3 else "running"
        return 200, {}, {"id": "j1", "status": status}

    slept = []
    monkeypatch.setattr(client, "_request", fake_request)
    monkeypatch.setattr(client.time, "sleep", slept.append)
    client.wait_jobs("http://x", ["j1"], timeout=60, poll_s=0.01)
    assert slept and all(s <= 0.01 for s in slept)


@pytest.mark.slow  # boots a real service + compiles its sweep (~13 s);
# the error-path contract runs under `make resume-smoke` (tier-1 trim,
# ISSUE 11 satellite)
def test_submit_exits_nonzero_on_failed_job(trace, tmp_path, monkeypatch):
    """A server-side job failure surfaces as JobsFailed carrying the
    done jobs' results, and `tpusim submit` exits nonzero while still
    printing the partial table."""
    import threading

    from tpusim.cli import main as cli_main
    from tpusim.svc.api import start_job_server
    from tpusim.svc.client import JobsFailed, submit_and_wait
    from tpusim.svc.worker import Worker

    real_dispatch = Worker._dispatch

    def poisoned(self, batch):
        # split by family: the worst-gpu_sel family is the poisoned one
        if batch[0].spec.gpu_sel == "worst":
            raise RuntimeError("poisoned family")
        return real_dispatch(self, batch)

    monkeypatch.setattr(Worker, "_dispatch", poisoned)
    srv, service, worker = start_job_server(
        str(tmp_path), {"default": trace}, listen=":0", lane_width=2,
        queue_size=8,
    )
    try:
        good = {"policies": FAM, "weights": [1000, 500], "seed": 1}
        bad = {"policies": FAM, "weights": [1000, 500], "seed": 1,
               "gpu_sel": "worst"}
        with pytest.raises(JobsFailed) as err:
            submit_and_wait(srv.url, [good, bad], timeout=120)
        assert len(err.value.failed) == 1
        assert "poisoned family" in err.value.failed[0]["error"]
        assert len(err.value.results) == 1  # the good job's result rode along
        assert err.value.results[0]["placed"] >= 0

        # the CLI surface: nonzero exit, partial table still printed
        jobs_file = tmp_path / "jobs.json"
        jobs_file.write_text(json.dumps([good, bad]))
        rc = cli_main(
            ["submit", str(jobs_file), "--url", srv.url,
             "--timeout", "120"]
        )
        assert rc == 1
    finally:
        worker.stop()
        srv.stop()
