"""Descheduler tests (ref semantics: deschedule.go + deschedule_utils.go)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpusim.constants import MILLI
from tpusim.io.trace import NodeRow, PodRow
from tpusim.sim.deschedule import (
    COS_SIM_CPU_BAR,
    eviction_scores,
    evict,
    select_victims,
)
from tpusim.sim.driver import Simulator, SimulatorConfig
from tpusim.types import PodSpec, make_node_state, make_typical_pods


def _cluster():
    # node 0: congested (little cpu left after pods), node 1: empty
    state = make_node_state(
        cpu_cap=[10000, 96000],
        mem_cap=[262144, 262144],
        gpu_cnt=[4, 8],
        gpu_type=[0, 0],
    )
    tp = make_typical_pods([(4000, 500, 1, 0, 0.6), (8000, 1000, 1, 0, 0.4)])
    return state, tp


def _place(state, pods, placed, dev_mask):
    """Apply placements by hand (tests drive the kernels directly)."""
    placed = jnp.asarray(placed)
    dev_mask = jnp.asarray(dev_mask)
    state = state._replace(
        cpu_left=state.cpu_left.at[placed].add(-pods.cpu),
        mem_left=state.mem_left.at[placed].add(-pods.mem),
        gpu_left=state.gpu_left.at[placed].add(
            -dev_mask.astype(jnp.int32) * pods.gpu_milli[:, None]
        ),
    )
    return state


def _pods(rows):
    cpu, milli, num, masks = zip(*rows)
    p = len(rows)
    dev = np.zeros((p, 8), bool)
    for i, m in enumerate(masks):
        dev[i, m] = True
    return (
        PodSpec(
            cpu=jnp.asarray(np.array(cpu, np.int32)),
            mem=jnp.asarray(np.zeros(p, np.int32)),
            gpu_milli=jnp.asarray(np.array(milli, np.int32)),
            gpu_num=jnp.asarray(np.array(num, np.int32)),
            gpu_mask=jnp.asarray(np.zeros(p, np.int32)),
            pinned=jnp.full(p, -1, jnp.int32),
        ),
        dev,
    )


def test_eviction_scores_roundtrip():
    state, tp = _cluster()
    pods, dev = _pods([(4000, 700, 1, [0]), (4000, 1000, 1, [1])])
    placed = np.array([0, 0], np.int32)
    state2 = _place(state, pods, placed, dev)
    new_frag, cos_sim, old_frag = eviction_scores(
        state2, pods, jnp.asarray(placed), jnp.asarray(dev), tp
    )
    # evicting pod 0 returns node 0 to "pod-1-only" occupancy; the frag score
    # must equal directly computing it on that intermediate state
    from tpusim.ops.frag import node_frag_score

    inter = _place(state, jax.tree.map(lambda a: a[1:], pods), placed[1:], dev[1:])
    want = node_frag_score(inter.cpu_left[0], inter.gpu_left[0], inter.gpu_type[0], tp)
    np.testing.assert_allclose(float(new_frag[0]), float(want), rtol=1e-6)
    assert 0.0 <= float(cos_sim[0]) <= 1.0
    assert old_frag.shape == (2,)


def test_evict_restores_resources():
    state, tp = _cluster()
    pods, dev = _pods([(4000, 700, 1, [0]), (2000, 500, 1, [1])])
    placed = np.array([0, 0], np.int32)
    state2 = _place(state, pods, placed, dev)
    restored = evict(state2, pods, placed, dev, [0, 1])
    np.testing.assert_array_equal(np.asarray(restored.cpu_left), np.asarray(state.cpu_left))
    np.testing.assert_array_equal(np.asarray(restored.gpu_left), np.asarray(state.gpu_left))


def test_cos_sim_only_congested_nodes():
    state, tp = _cluster()
    # node 0: cpu_left 10000-9000=1000 < bar, device 0 has 300 left (< bar),
    # device 1 fully free (> bar) → passes both filters
    pods, dev = _pods([(9000, 700, 1, [0]), (2000, 500, 1, [2])])
    placed = np.array([0, 1], np.int32)
    state2 = _place(state, pods, placed, dev)
    victims = select_victims(
        state2, pods, placed, dev, tp, "cosSim", ratio=1.0,
        node_names=["a", "b"],
    )
    # only node 0 is congested; its single pod is the victim. node 1 has
    # plenty of cpu left so pod 1 is never descheduled.
    assert victims == [0]


def test_frag_one_pod_needs_positive_gain():
    state, tp = _cluster()
    pods, dev = _pods([(4000, 700, 1, [0])])
    placed = np.array([0], np.int32)
    state2 = _place(state, pods, placed, dev)
    new_frag, _, old_frag = (
        np.asarray(x)
        for x in eviction_scores(state2, pods, jnp.asarray(placed), jnp.asarray(dev), tp)
    )
    victims = select_victims(
        state2, pods, placed, dev, tp, "fragOnePod", ratio=1.0
    )
    gain = int(old_frag[0] - new_frag[0])
    assert (victims == [0]) == (gain > 0)


def test_frag_multi_pod_budget_and_revisit():
    state, tp = _cluster()
    pods, dev = _pods(
        [(1000, 700, 1, [0]), (1000, 700, 1, [1]), (1000, 700, 1, [2])]
    )
    placed = np.array([0, 0, 0], np.int32)
    state2 = _place(state, pods, placed, dev)
    victims = select_victims(
        state2, pods, placed, dev, tp, "fragMultiPod", ratio=0.67
    )
    assert len(victims) <= 2  # ceil(0.67*3) = 3 but budget caps evictions
    assert len(set(victims)) == len(victims)


@pytest.mark.slow
def test_driver_deschedule_end_to_end():
    """resume-smoke only (ISSUE 17 tier-1 buyback): tier-1's driver-
    deschedule representative is test_deschedule_reschedule_emits_per_
    event_reports (same driver + deschedule_cluster path, same shapes);
    the conservation assertions here ride resume-smoke."""
    nodes = [
        NodeRow("n0", 32000, 262144, 4, "A100"),
        NodeRow("n1", 32000, 262144, 4, "A100"),
    ]
    pods = [
        PodRow(f"p{i}", 2000, 1024, 1, 700, "", creation_time=i) for i in range(6)
    ]
    cfg = SimulatorConfig(
        policies=(("FGDScore", 1000),),
        deschedule_policy="fragOnePod",
        deschedule_ratio=0.5,
        report_per_event=False,
    )
    sim = Simulator(nodes, cfg)
    sim.set_workload_pods(pods)
    res = sim.run()
    before_placed = int((res.placed_node >= 0).sum())
    failed = sim.deschedule_cluster()
    sim.cluster_analysis("PostDeschedule")
    after_placed = int((sim.last_result.placed_node >= 0).sum())
    # conservation: every pod is placed or accounted as unscheduled
    assert after_placed + len(sim.last_result.unscheduled_pods) == len(pods)
    assert after_placed >= before_placed - len(failed)
    # resource conservation on the final state
    s = sim.last_result.state
    used_cpu = int((s.cpu_cap - s.cpu_left).sum())
    assert used_cpu == 2000 * after_placed


def test_deschedule_reschedule_emits_per_event_reports():
    """The victim reschedule goes through the reporting loop in the
    reference (deschedule.go:91 → SchedulePods), so per-event [Report]
    lines must cover those events too."""
    from tpusim.io.trace import NodeRow, PodRow
    from tpusim.sim.driver import Simulator, SimulatorConfig

    # nodes end up CPU-congested (< the cosSim 2000-milli bar) with free
    # GPU milli, the precondition for cosSim victim selection
    nodes = [NodeRow("n0", 13000, 262144, 4, "V100M16"),
             NodeRow("n1", 13000, 262144, 4, "V100M16")]
    pods = [
        PodRow(f"p{i}", 4000, 1024, 1, 500, "", creation_time=i)
        for i in range(6)
    ]
    cfg = SimulatorConfig(
        policies=(("FGDScore", 1000),),
        gpu_sel_method="FGDScore",
        deschedule_ratio=0.4,
        deschedule_policy="cosSim",
    )
    sim = Simulator(nodes, cfg)
    sim.set_workload_pods(pods)
    res = sim.run()
    base = sim.log.dump().count("(origin)")
    assert base == res.events

    sim.deschedule_cluster()
    text = sim.log.dump()
    assert "Num of Descheduled Pods: 2" in text  # ceil(0.4 * 6) placed... 2
    assert text.count("(origin)") == base + 2  # victim reschedule reported


def test_inflation_emits_per_event_reports():
    """Inflation scheduling reports per event and prints the failed-pods
    detail block (ref: simulator.go:1023-1024 SchedulePods +
    ReportFailedPods)."""
    from tpusim.io.trace import NodeRow, PodRow
    from tpusim.sim.driver import Simulator, SimulatorConfig

    nodes = [NodeRow("n0", 64000, 262144, 8, "V100M16")]
    pods = [
        PodRow(f"p{i}", 2000, 1024, 1, 500, "", creation_time=i)
        for i in range(4)
    ]
    cfg = SimulatorConfig(
        policies=(("BestFitScore", 1000),), inflation_ratio=2.0
    )
    sim = Simulator(nodes, cfg)
    sim.set_workload_pods(pods)
    res = sim.run()
    base = sim.log.dump().count("(origin)")
    assert base == res.events

    sim.run_workload_inflation_evaluation("ScheduleInflation")
    text = sim.log.dump()
    assert text.count("(origin)") > base  # inflation events reported
    assert "Cluster Analysis Results (ScheduleInflation)" in text


# ---- edge cases (ISSUE 2 satellite): empty node, budget 0, all-pinned
# pods, and tie-break determinism across the three victim policies ----


def _edge_cluster():
    """Two loaded nodes + one completely empty node, with pods placed so
    every policy has candidates; node 2 stays empty."""
    state = make_node_state(
        cpu_cap=[10000, 10000, 64000],
        mem_cap=[262144, 262144, 262144],
        gpu_cnt=[4, 4, 8],
        gpu_type=[0, 0, 0],
    )
    tp = make_typical_pods([(4000, 500, 1, 0, 0.6), (8000, 1000, 1, 0, 0.4)])
    pods, dev = _pods(
        [
            (4000, 700, 1, [0]),
            (4000, 1000, 1, [1]),
            (4000, 700, 1, [0]),
            (4000, 1000, 1, [1]),
        ]
    )
    placed = np.array([0, 0, 1, 1], np.int32)
    state = _place(state, pods, placed, dev)
    return state, tp, pods, placed, dev


@pytest.mark.parametrize("policy", ["cosSim", "fragOnePod", "fragMultiPod"])
def test_select_victims_budget_zero(policy):
    """ratio 0 -> budget 0 -> no victims, for every policy (deschedule.go:27
    computes the budget before any policy logic runs)."""
    state, tp, pods, placed, dev = _edge_cluster()
    assert select_victims(state, pods, placed, dev, tp, policy, 0.0) == []


@pytest.mark.parametrize("policy", ["cosSim", "fragOnePod", "fragMultiPod"])
def test_select_victims_nothing_placed(policy):
    """An all-idle cluster (every placed == -1) has nothing to deschedule;
    the batched scorer must not be tripped by the clamped -1 gathers."""
    state, tp, pods, _, dev = _edge_cluster()
    placed = np.full(4, -1, np.int32)
    assert select_victims(state, pods, placed, dev, tp, policy, 0.5) == []


@pytest.mark.parametrize("policy", ["cosSim", "fragOnePod", "fragMultiPod"])
def test_select_victims_skips_empty_node(policy):
    """Policies walk nodes without pods (node 2 here) without crashing and
    never name a victim from them."""
    state, tp, pods, placed, dev = _edge_cluster()
    victims = select_victims(state, pods, placed, dev, tp, policy, 1.0)
    assert all(0 <= v < 4 for v in victims)
    # every victim really was placed somewhere
    assert all(placed[v] >= 0 for v in victims)


@pytest.mark.parametrize("policy", ["cosSim", "fragOnePod", "fragMultiPod"])
def test_select_victims_all_pinned(policy):
    """nodeSelector-pinned pods are NOT exempt from descheduling (the
    reference's victim walks never consult the selector) — an all-pinned
    workload must still yield victims, deterministically."""
    state, tp, pods, placed, dev = _edge_cluster()
    pods = pods._replace(pinned=jnp.asarray(placed))  # pin each to its node
    a = select_victims(state, pods, placed, dev, tp, policy, 1.0)
    b = select_victims(state, pods, placed, dev, tp, policy, 1.0)
    assert a == b
    assert len(a) > 0 or policy == "cosSim"  # cosSim may find no congestion


@pytest.mark.parametrize("policy", ["cosSim", "fragOnePod", "fragMultiPod"])
def test_select_victims_tiebreak_determinism(policy):
    """Symmetric clusters (identical nodes, identical pods) are pure
    tie-break territory: the victim list must be identical across repeated
    calls AND insensitive to jax/numpy evaluation noise — the policies
    break ties by stable sort order / node name, never dict order."""
    state = make_node_state(
        cpu_cap=[10000, 10000],
        mem_cap=[262144, 262144],
        gpu_cnt=[4, 4],
        gpu_type=[0, 0],
    )
    tp = make_typical_pods([(4000, 500, 1, 0, 1.0)])
    pods, dev = _pods(
        [(4000, 700, 1, [0]), (4000, 700, 1, [0])]
    )
    placed = np.array([0, 1], np.int32)
    state = _place(state, pods, placed, dev)
    names = ["node-b", "node-a"]  # deliberately not in index order
    runs = [
        select_victims(
            state, pods, placed, dev, tp, policy, 0.5, node_names=names
        )
        for _ in range(3)
    ]
    assert runs[0] == runs[1] == runs[2]
    assert len(runs[0]) <= 1  # budget = ceil(0.5 * 2) = 1
