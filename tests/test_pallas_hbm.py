"""HBM-residency fused Pallas engine (ENGINES.md Round 19): the
[K, N] score/sdev/feas tables live in HBM (`TPUMemorySpace.ANY`) with
per-event double-buffered DMA, selectHost runs over VMEM-resident block
summaries — and placements/devices/failure flags/final state must stay
bit-identical to the (blocked) table engine.

The CPU lane runs the kernel in Pallas interpreter mode (the Mosaic +
real-DMA path needs TPU hardware; real-chip numbers are advisory).
Interpreter steps are slow, so the tier-1 slice uses small multi-chunk
traces plus the double-buffer boundary cases and the two-tier footprint
math; the above-the-old-ceiling N ∈ {5000, 8192} acceptance runs are
slow-marked into `make resume-smoke` (the ROADMAP tier-1 budget rule).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import random_cluster, random_pods
from tests.test_table_engine import _assert_equal, _events_with_deletes
from tpusim.policies import make_policy
from tpusim.sim.engine import EV_CREATE
from tpusim.sim import pallas_engine
from tpusim.sim.pallas_engine import make_pallas_replay
from tpusim.sim.table_engine import build_pod_types, make_table_replay
from tpusim.types import PodSpec

# module-level policy lists: the replay cache keys on the policy fn
# OBJECTS, so sharing them across tests shares one traced replayer per
# shape instead of re-tracing per test
_FGD = [(make_policy("FGDScore"), 1000)]
_BESTFIT = [(make_policy("BestFitScore"), 1000)]
_MIX = [(make_policy("PWRScore"), 500), (make_policy("FGDScore"), 500)]


def _run_both(policies, gpu_sel, state, tp, pods, ev_kind, ev_pod, rank,
              block_size=128):
    """(blocked table engine, hbm pallas) results + the DMA stats row."""
    key = jax.random.PRNGKey(3)
    types = build_pod_types(pods)
    tab = make_table_replay(policies, gpu_sel=gpu_sel,
                            block_size=block_size)
    r0 = tab(state, pods, types, ev_kind, ev_pod, tp, key, rank)
    hbm = make_pallas_replay(policies, gpu_sel=gpu_sel, interpret=True,
                             residency="hbm")
    r1, dma = hbm(state, pods, types, ev_kind, ev_pod, tp, key, rank)
    return r0, r1, np.asarray(dma)


def _check(r0, r1, dma):
    _assert_equal(r0, r1)
    assert np.array_equal(np.asarray(r0.event_node),
                          np.asarray(r1.event_node))
    assert np.array_equal(np.asarray(r0.event_dev),
                          np.asarray(r1.event_dev))
    # every started DMA was waited — the kernel leaks no transfers
    assert dma[0] == dma[1] and dma[1] > 0


def _pods_k_types(k, rng):
    """Exactly k DISTINCT pod types (cpu strictly increasing per type)
    spanning cpu-only / share / whole kinds — the K = 151 acceptance
    shape without relying on random dedup."""
    kind = rng.integers(0, 3, k)
    cpu = (1000 + 100 * np.arange(k)).astype(np.int32)
    mem = rng.choice([1024, 4096, 16384], k).astype(np.int32)
    gpu_milli = np.where(
        kind == 1, rng.choice([100, 250, 500, 750], k), 1000
    ).astype(np.int32)
    gpu_milli = np.where(kind == 0, 0, gpu_milli)
    gpu_num = np.where(
        kind == 2, rng.choice([1, 2, 4], k), np.where(kind == 1, 1, 0)
    ).astype(np.int32)
    return PodSpec(
        cpu=jnp.asarray(cpu),
        mem=jnp.asarray(mem),
        gpu_milli=jnp.asarray(gpu_milli),
        gpu_num=jnp.asarray(gpu_num),
        gpu_mask=jnp.zeros(k, jnp.int32),
        pinned=jnp.full(k, -1, jnp.int32),
    )


def test_hbm_matches_blocked_engine_multichunk():
    """N = 512 (4 lane-chunks): the full DMA choreography — dirty-column
    writeback, row-slice prefetch + patch, summary maintenance, drift
    rebuild — against the blocked table engine, bit-exact, for a
    normalize=none policy and a minmax one."""
    rng = np.random.default_rng(11)
    state, tp = random_cluster(rng, num_nodes=512)
    pods = random_pods(rng, num_pods=64)
    ev_kind, ev_pod = _events_with_deletes(64, rng)
    rank = jnp.asarray(rng.permutation(512).astype(np.int32))
    for policies, gpu_sel in ((_FGD, "FGDScore"), (_BESTFIT, "best")):
        r0, r1, dma = _run_both(
            policies, gpu_sel, state, tp, pods, ev_kind, ev_pod, rank
        )
        _check(r0, r1, dma)


@pytest.mark.slow  # tier-1 trim, ISSUE 16: rides resume-smoke
def test_hbm_same_block_twice_and_edges():
    """Double-buffer boundary cases: consecutive events touching the SAME
    128-node block (pinned pods force it — the row-slice prefetch left
    HBM before that column's refresh, so only the in-VMEM patch can keep
    it current), a delete immediately re-touching the block it freed,
    and the first/last-event edges (init builds + final writeback
    waits)."""
    rng = np.random.default_rng(17)
    state, tp = random_cluster(rng, num_nodes=200)  # 2 chunks
    pods = random_pods(rng, num_pods=12)
    # pin pods 0..3 to nodes in BOTH chunks: same-chunk twice (3, 7),
    # then a chunk hop (140), then back (9); the rest select freely.
    # The pinned pods are tiny cpu-only requests so every node hosts
    # them — the pins decide, not feasibility
    small = jnp.asarray([1000] * 4 + [0] * 8, jnp.int32)
    sel4 = jnp.arange(12) < 4
    pods = pods._replace(
        cpu=jnp.where(sel4, small, pods.cpu),
        mem=jnp.where(sel4, 512, pods.mem),
        gpu_milli=jnp.where(sel4, 0, pods.gpu_milli),
        gpu_num=jnp.where(sel4, 0, pods.gpu_num),
        gpu_mask=jnp.where(sel4, 0, pods.gpu_mask),
        pinned=pods.pinned.at[0].set(3).at[1].set(7).at[2].set(140)
        .at[3].set(9),
    )
    kinds = [0, 0, 0, 0, 1, 0, 0, 1, 0, 0, 0, 0]
    idxs = [0, 1, 2, 3, 1, 4, 5, 0, 6, 7, 8, 9]
    ev_kind = jnp.asarray(kinds, jnp.int32)
    ev_pod = jnp.asarray(idxs, jnp.int32)
    rank = jnp.asarray(rng.permutation(200).astype(np.int32))
    r0, r1, dma = _run_both(_FGD, "FGDScore", state, tp, pods, ev_kind,
                            ev_pod, rank)
    _check(r0, r1, dma)
    # binds actually landed on the pinned nodes (same-block-twice hit;
    # pods 0/1 are later deleted, so check the event telemetry)
    ev_nodes = np.asarray(r1.event_node)
    assert ev_nodes[0] == 3 and ev_nodes[1] == 7
    assert ev_nodes[2] == 140 and ev_nodes[3] == 9


def test_hbm_single_event():
    """E = 1: init + one event + final writeback wait, no prefetch ever
    started — the kernel must not deadlock on unsignaled semaphores."""
    rng = np.random.default_rng(3)
    state, tp = random_cluster(rng, num_nodes=130)
    pods = random_pods(rng, num_pods=1)
    rank = jnp.asarray(rng.permutation(130).astype(np.int32))
    ev_kind = jnp.zeros(1, jnp.int32)
    ev_pod = jnp.zeros(1, jnp.int32)
    r0, r1, dma = _run_both(_FGD, "FGDScore", state, tp, pods, ev_kind,
                            ev_pod, rank)
    _check(r0, r1, dma)


def test_two_tier_fits_vmem_boundary():
    """The residency select's boundary math: exact byte thresholds flip
    each tier, and the documented HBM ceiling at K = 151 clears 256k."""
    shape = (4096, 151, 1, 2048, 4096)
    v = pallas_engine.vmem_resident_bytes(*shape)
    h = pallas_engine.vmem_resident_bytes_hbm(*shape, num_norm=1)
    assert h < v  # the whole point: the HBM tier's working set shrinks

    import os
    budget = os.environ.get("TPUSIM_PALLAS_VMEM_BYTES")
    try:
        os.environ["TPUSIM_PALLAS_VMEM_BYTES"] = str(v)
        assert pallas_engine.fits_vmem(*shape)
        assert pallas_engine.select_residency(*shape) == "vmem"
        os.environ["TPUSIM_PALLAS_VMEM_BYTES"] = str(v - 1)
        assert not pallas_engine.fits_vmem(*shape)
        assert pallas_engine.select_residency(*shape, num_norm=1) == "hbm"
        os.environ["TPUSIM_PALLAS_VMEM_BYTES"] = str(h)
        assert pallas_engine.fits_hbm(*shape, num_norm=1)
        os.environ["TPUSIM_PALLAS_VMEM_BYTES"] = str(h - 1)
        assert not pallas_engine.fits_hbm(*shape, num_norm=1)
        assert pallas_engine.select_residency(*shape, num_norm=1) is None
        # ceiling under the threshold budget is a pure function of it
        assert pallas_engine.hbm_ceiling_nodes(
            151, 1, 1, 2048, 4096, budget=h
        ) >= 4096
    finally:
        if budget is None:
            os.environ.pop("TPUSIM_PALLAS_VMEM_BYTES", None)
        else:
            os.environ["TPUSIM_PALLAS_VMEM_BYTES"] = budget

    # the default-budget auto-select at the acceptance shapes: old
    # ceiling -> vmem; above it -> hbm; genuinely impossible -> None
    assert pallas_engine.select_residency(512, 151, 1, 2048, 4096) == "vmem"
    assert pallas_engine.select_residency(8192, 151, 1, 2048, 4096) == "hbm"
    assert pallas_engine.select_residency(10**6, 151, 1, 2048, 4096) is None
    # the ROADMAP/ISSUE headline: HBM ceiling >= 256k at K = 151
    assert pallas_engine.hbm_ceiling_nodes(151, 1, 1) >= 256 * 1024
    assert pallas_engine.hbm_ceiling_nodes(151, 2, 2) >= 128 * 1024


def test_vmem_budget_env_fails_loudly(monkeypatch):
    """TPUSIM_PALLAS_VMEM_BYTES with a non-integer value raises NAMING
    the variable (the shared tpusim.envutil helper) instead of silently
    reverting to the default — at every consumer of the budget."""
    monkeypatch.setenv("TPUSIM_PALLAS_VMEM_BYTES", "14MB")
    with pytest.raises(ValueError, match="TPUSIM_PALLAS_VMEM_BYTES"):
        pallas_engine.vmem_budget()
    with pytest.raises(ValueError, match="TPUSIM_PALLAS_VMEM_BYTES"):
        pallas_engine.fits_vmem(512, 10, 1, 64, 64)
    with pytest.raises(ValueError, match="TPUSIM_PALLAS_VMEM_BYTES"):
        pallas_engine.fits_hbm(512, 10, 1, 64, 64)
    monkeypatch.setenv("TPUSIM_PALLAS_VMEM_BYTES", "-5")
    with pytest.raises(ValueError, match="TPUSIM_PALLAS_VMEM_BYTES"):
        pallas_engine.vmem_budget()
    monkeypatch.setenv("TPUSIM_PALLAS_VMEM_BYTES", str(2**24))
    assert pallas_engine.vmem_budget() == 2**24
    # the lease knobs ride the same shared helper (one validation path)
    from tpusim.svc import leases

    monkeypatch.setenv("TPUSIM_LEASE_SKEW_S", "soon")
    with pytest.raises(ValueError, match="TPUSIM_LEASE_SKEW_S"):
        leases.lease_skew_s()


def test_driver_residency_knob():
    """SimulatorConfig.table_residency routes the fused-engine dispatch:
    a forced 'hbm' run (CPU -> interpreter) reproduces forced 'table'
    exactly through the full driver path, the obs record carries the
    residency + exact DMA counters, and bad knobs raise at
    construction."""
    from tests.test_batch import _mk_cluster, _mk_pods
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.typical import TypicalPodsConfig

    rng = np.random.default_rng(23)
    nodes = _mk_cluster(rng)
    pods = _mk_pods(rng, n=24)

    def run(engine, residency):
        cfg = SimulatorConfig(
            policies=(("FGDScore", 1000),),
            gpu_sel_method="FGDScore",
            shuffle_pod=True,
            seed=42,
            report_per_event=False,
            engine=engine,
            table_residency=residency,
            typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
        )
        sim = Simulator(nodes, cfg)
        sim.set_workload_pods(pods)
        return sim, sim.run()

    s_t, r_t = run("table", "auto")
    s_h, r_h = run("pallas", "hbm")
    assert s_h._last_engine == "pallas (hbm)"
    assert not any("[Degrade]" in l for l in s_h.log.lines)
    assert np.array_equal(r_t.placed_node, r_h.placed_node)
    assert np.array_equal(r_t.dev_mask, r_h.dev_mask)
    det = s_h.run_telemetry().to_record()["deterministic"]
    assert det["pallas_residency"] == "hbm"
    assert det["counts"]["pallas_dma_waits"] > 0
    assert det["counts"]["pallas_dma_waits"] == \
        det["counts"]["pallas_dma_starts"]

    from tpusim.sim.driver import Simulator as S, SimulatorConfig as C

    with pytest.raises(ValueError, match="table_residency"):
        S(nodes, C(table_residency="sram"))


@pytest.mark.slow  # interpreter compile + N-sized DMAs: resume-smoke lane
@pytest.mark.parametrize(
    "n_nodes,policies,gpu_sel",
    [
        (5000, _BESTFIT, "best"),
        (8192, _FGD, "FGDScore"),
        (8192, _MIX, "FGDScore"),
    ],
    ids=("5000-bestfit", "8192-fgd", "8192-pwr+fgd"),
)
def test_hbm_above_old_ceiling(n_nodes, policies, gpu_sel):
    """The acceptance pin: N ∈ {5000, 8192} at K = 151 — ABOVE the
    N ≤ 4096 VMEM ceiling — replayed by the HBM-residency kernel in
    interpreter mode, bit-identical to the blocked table engine across
    policy/mix/gpu_sel, with the residency select routing 'hbm'."""
    rng = np.random.default_rng(31)
    state, tp = random_cluster(rng, num_nodes=n_nodes)
    pods = _pods_k_types(151, rng)
    types = build_pod_types(pods)
    k = int(types.share.cpu.shape[0]) + int(types.whole.cpu.shape[0])
    assert k == 151
    ev_kind, ev_pod = _events_with_deletes(151, rng)
    rank = jnp.asarray(rng.permutation(n_nodes).astype(np.int32))
    res = pallas_engine.select_residency(
        n_nodes, k, len(policies), 151, int(ev_kind.shape[0]),
        pallas_engine.num_normalized(policies),
    )
    # N=8192 at K=151 is past the VMEM tier — auto-select must route
    # hbm; N=5000 still fits VMEM at this tiny workload (the old 4096
    # "ceiling" was measured at openb's event/pod sizes), so the select
    # just must not degrade. The replay below forces the HBM kernel
    # either way — the bit-identity claim is residency-independent.
    assert res == "hbm" if n_nodes >= 8192 else res is not None
    r0, r1, dma = _run_both(policies, gpu_sel, state, tp, pods, ev_kind,
                            ev_pod, rank)
    _check(r0, r1, dma)


@pytest.mark.slow  # full driver path at N=8192: resume-smoke lane
def test_driver_8192_runs_hbm_without_degrading():
    """Driver-level acceptance: a forced pallas engine at N = 8192 /
    K = 151 no longer prints [Degrade] — the auto residency select
    lands on the HBM tier and the run reconciles the table engine
    bit-exactly."""
    from tpusim.io.trace import NodeRow
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.typical import TypicalPodsConfig
    from tpusim.io.trace import PodRow

    rng = np.random.default_rng(7)
    gpus = rng.choice([0, 2, 4, 8], 8192)
    nodes = [
        NodeRow(
            f"n{i:05d}",
            int(rng.choice([32000, 64000, 96000])),
            int(rng.choice([131072, 262144])),
            int(g),
            ["2080", "T4", "V100M16"][i % 3] if g else "",
        )
        for i, g in enumerate(gpus)
    ]
    kinds = rng.integers(0, 3, 151)
    pods = [
        PodRow(
            f"p{i:04d}",
            1000 + 100 * i,
            int(rng.choice([1024, 4096])),
            (0 if kinds[i] == 0 else 1 if kinds[i] == 1
             else int(rng.choice([1, 2]))),
            (0 if kinds[i] == 0
             else int(rng.choice([250, 500])) if kinds[i] == 1
             else 1000),
        )
        for i in range(151)
    ]

    def run(engine):
        cfg = SimulatorConfig(
            policies=(("FGDScore", 1000),),
            gpu_sel_method="FGDScore",
            seed=42,
            report_per_event=False,
            engine=engine,
            typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
        )
        sim = Simulator(nodes, cfg)
        sim.set_workload_pods(pods)
        return sim, sim.run()

    s_h, r_h = run("pallas")
    assert s_h._last_engine == "pallas (hbm)"
    assert not any("[Degrade]" in l for l in s_h.log.lines)
    s_t, r_t = run("table")
    assert np.array_equal(r_t.placed_node, r_h.placed_node)
    assert np.array_equal(r_t.dev_mask, r_h.dev_mask)


@pytest.mark.slow  # tier-1 trim, ISSUE 16: rides resume-smoke
def test_hbm_two_normalized_policies():
    """nn = 2 (BestFit minmax + PWR pwr in one mix): two brmin/brmax
    summary slots, two stored-extrema lanes, independent drift
    channels — the widest normalizer shape the column registry can
    express, bit-identical to the blocked table engine."""
    rng = np.random.default_rng(53)
    state, tp = random_cluster(rng, num_nodes=160)
    pods = random_pods(rng, num_pods=48)
    ev_kind, ev_pod = _events_with_deletes(48, rng)
    rank = jnp.asarray(rng.permutation(160).astype(np.int32))
    policies = [(make_policy("BestFitScore"), 400),
                (make_policy("PWRScore"), 600)]
    assert pallas_engine.num_normalized(policies) == 2
    r0, r1, dma = _run_both(policies, "PWRScore", state, tp, pods,
                            ev_kind, ev_pod, rank)
    _check(r0, r1, dma)
    assert dma[2] > 0  # at least one extrema-drift rebuild fired
