"""Live-cluster kube-client path (tpusim.io.kube_client): integration-tested
against a recorded API fixture — a local HTTP server replaying canned list
responses — asserting CreateClusterResourceFromClient's semantics
(simulator.go:746-891): all nodes kept, only static raw pods kept,
workloads re-expanded, Deployment-owned ReplicaSets and CronJob-owned Jobs
skipped, version-fallback endpoints tolerated."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest
import yaml

from tpusim.io.kube_client import (
    KubeClient,
    KubeClientError,
    is_kubeconfig_file,
    load_cluster_from_client,
)


def _node(name, cpu="32000m", mem="131072Mi", gpus=2, model="V100M16"):
    return {
        "metadata": {
            "name": name,
            "labels": {"alibabacloud.com/gpu-card-model": model},
        },
        "status": {
            "allocatable": {
                "cpu": cpu,
                "memory": mem,
                "alibabacloud.com/gpu-count": str(gpus),
            }
        },
    }


FIXTURE = {
    "/api/v1/nodes": {
        "apiVersion": "v1",
        "kind": "NodeList",
        "items": [_node("node-b"), _node("node-a")],
    },
    "/api/v1/pods": {
        "apiVersion": "v1",
        "kind": "PodList",
        "items": [
            {   # static pod (mirror annotation) -> kept
                "metadata": {
                    "name": "etcd-node-a",
                    "namespace": "kube-system",
                    "annotations": {
                        "kubernetes.io/config.mirror": "abc",
                    },
                },
                "spec": {
                    "containers": [
                        {"resources": {"requests": {"cpu": "500m"}}}
                    ]
                },
            },
            {   # regular pod -> dropped (workloads re-expand)
                "metadata": {"name": "web-123", "namespace": "default"},
                "spec": {
                    "containers": [
                        {"resources": {"requests": {"cpu": "1000m"}}}
                    ]
                },
            },
        ],
    },
    # policy/v1beta1 404s (modern cluster); policy/v1 responds
    "/apis/policy/v1/poddisruptionbudgets": {
        "apiVersion": "policy/v1",
        "kind": "PodDisruptionBudgetList",
        "items": [],
    },
    "/api/v1/services": {"kind": "ServiceList", "items": []},
    "/apis/storage.k8s.io/v1/storageclasses": {
        "kind": "StorageClassList",
        "items": [],
    },
    "/api/v1/persistentvolumeclaims": {
        "kind": "PersistentVolumeClaimList",
        "items": [],
    },
    "/api/v1/replicationcontrollers": {
        "kind": "ReplicationControllerList",
        "items": [],
    },
    "/apis/apps/v1/deployments": {
        "apiVersion": "apps/v1",
        "kind": "DeploymentList",
        "items": [
            {
                "metadata": {"name": "web", "namespace": "default"},
                "spec": {
                    "replicas": 2,
                    "template": {
                        "metadata": {
                            "annotations": {
                                "alibabacloud.com/gpu-milli": "500",
                                "alibabacloud.com/gpu-count": "1",
                            }
                        },
                        "spec": {
                            "containers": [
                                {
                                    "resources": {
                                        "requests": {
                                            "cpu": "2000m",
                                            "memory": "4096Mi",
                                        }
                                    }
                                }
                            ]
                        },
                    },
                },
            }
        ],
    },
    "/apis/apps/v1/replicasets": {
        "apiVersion": "apps/v1",
        "kind": "ReplicaSetList",
        "items": [
            {   # deployment-owned -> skipped (ownedByDeployment)
                "metadata": {
                    "name": "web-6f9",
                    "namespace": "default",
                    "ownerReferences": [{"kind": "Deployment", "name": "web"}],
                },
                "spec": {"replicas": 2, "template": {"spec": {"containers": []}}},
            },
            {   # standalone RS -> expands
                "metadata": {"name": "solo-rs", "namespace": "default"},
                "spec": {
                    "replicas": 1,
                    "template": {
                        "spec": {
                            "containers": [
                                {"resources": {"requests": {"cpu": "1000m"}}}
                            ]
                        }
                    },
                },
            },
        ],
    },
    "/apis/apps/v1/statefulsets": {"kind": "StatefulSetList", "items": []},
    "/apis/apps/v1/daemonsets": {"kind": "DaemonSetList", "items": []},
    # both cronjob endpoints 404: optional group absent entirely
    "/apis/batch/v1/jobs": {
        "apiVersion": "batch/v1",
        "kind": "JobList",
        "items": [
            {   # cronjob-owned -> skipped (ownedByCronJob)
                "metadata": {
                    "name": "nightly-1",
                    "ownerReferences": [{"kind": "CronJob", "name": "nightly"}],
                },
                "spec": {"template": {"spec": {"containers": []}}},
            }
        ],
    },
}


# paths the handler answers with 403 (RBAC denial) instead of the fixture
FORBIDDEN: set = set()


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):
        path = self.path.split("?")[0]
        if path in FORBIDDEN:
            self.send_response(403)
            self.end_headers()
            return
        body = FIXTURE.get(path)
        if body is None:
            self.send_response(404)
            self.end_headers()
            return
        data = json.dumps(body).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):  # quiet
        pass


@pytest.fixture(scope="module")
def api_server():
    srv = HTTPServer(("127.0.0.1", 0), _Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def _kubeconfig(tmp_path, server, token="secret-token"):
    p = tmp_path / "kubeconfig"
    p.write_text(
        yaml.dump(
            {
                "apiVersion": "v1",
                "kind": "Config",
                "current-context": "sim",
                "clusters": [{"name": "c", "cluster": {"server": server}}],
                "users": [{"name": "u", "user": {"token": token}}],
                "contexts": [
                    {"name": "sim", "context": {"cluster": "c", "user": "u"}}
                ],
            }
        )
    )
    return str(p)


def test_is_kubeconfig_file(tmp_path, api_server):
    kc = _kubeconfig(tmp_path, api_server)
    assert is_kubeconfig_file(kc)
    dump = tmp_path / "dump.yaml"
    dump.write_text(yaml.dump({"kind": "List", "items": []}))
    assert not is_kubeconfig_file(str(dump))


@pytest.mark.slow  # tier-1 trim, ISSUE 16: rides resume-smoke
def test_is_kubeconfig_file_large_files(tmp_path, api_server):
    """Size alone must not route a file: a multi-MB multi-cluster
    kubeconfig still goes to the client path, while a multi-MB dump skips
    the full parse via the head-of-file marker scan."""
    big_kc = tmp_path / "big-kubeconfig"
    doc = yaml.safe_load(open(_kubeconfig(tmp_path, api_server)))
    doc["clusters"] += [
        {"name": f"c{i}", "cluster": {"server": f"https://h{i}:6443",
                                      "certificate-authority-data": "x" * 4096}}
        for i in range(400)
    ]
    big_kc.write_text(yaml.dump(doc))
    assert big_kc.stat().st_size > 1 << 20
    assert is_kubeconfig_file(str(big_kc))

    big_dump = tmp_path / "big-dump.yaml"
    big_dump.write_text(
        yaml.dump({"kind": "List", "items": [_node(f"n{i}") for i in range(8000)]})
    )
    assert big_dump.stat().st_size > 1 << 20
    assert not is_kubeconfig_file(str(big_dump))

    # inconclusive head: a >1MB kubeconfig whose huge `users:` block
    # (embedded certs) precedes both positive markers must fall back to the
    # full parse, not be misrouted to dump ingestion (ADVICE r4)
    tail_kc = tmp_path / "markers-past-head"
    doc = yaml.safe_load(open(_kubeconfig(tmp_path, api_server)))
    users_first = {
        "apiVersion": "v1",
        "users": [
            {"name": f"u{i}", "user": {"client-certificate-data": "x" * 4096}}
            for i in range(400)
        ],
        "contexts": doc["contexts"],
        "current-context": doc["current-context"],
        "clusters": doc["clusters"],
        "kind": "Config",
    }
    tail_kc.write_text(yaml.dump(users_first, sort_keys=False))
    assert tail_kc.stat().st_size > 1 << 20
    head = tail_kc.read_text()[: 64 << 10]
    assert "kind: Config" not in head and "\nclusters:" not in head
    assert is_kubeconfig_file(str(tail_kc))


def test_client_403_falls_through_to_next_candidate(tmp_path, api_server):
    """An RBAC-denied deprecated group-version must not abort ingestion
    when the current group-version is listable (ADVICE r3)."""
    kc = _kubeconfig(tmp_path, api_server)
    FORBIDDEN.add("/apis/policy/v1beta1/poddisruptionbudgets")
    try:
        cluster = load_cluster_from_client(kc)
        assert [n.name for n in cluster.nodes] == ["node-a", "node-b"]
    finally:
        FORBIDDEN.clear()


def test_client_all_candidates_denied_raises(tmp_path, api_server):
    """If every candidate endpoint is RBAC-denied the client must raise —
    even for optional groups, silence would drop real objects."""
    kc = _kubeconfig(tmp_path, api_server)
    FORBIDDEN.update(
        {
            "/apis/policy/v1beta1/poddisruptionbudgets",
            "/apis/policy/v1/poddisruptionbudgets",
        }
    )
    try:
        with pytest.raises(KubeClientError, match="PodDisruptionBudget"):
            load_cluster_from_client(kc)
    finally:
        FORBIDDEN.clear()


def test_client_lists_and_filters(tmp_path, api_server):
    kc = _kubeconfig(tmp_path, api_server)
    cluster = load_cluster_from_client(kc)
    # nodes: all kept, name-sorted
    assert [n.name for n in cluster.nodes] == ["node-a", "node-b"]
    assert cluster.nodes[0].gpu == 2 and cluster.nodes[0].model == "V100M16"
    names = sorted(p.name for p in cluster.pods)
    # static pod kept; regular raw pod dropped; deployment expands 2
    # replicas; standalone RS expands 1; deployment-owned RS and
    # cronjob-owned Job contribute nothing
    assert "kube-system/etcd-node-a" in names
    assert not any("web-123" in n for n in names)
    dep_pods = [n for n in names if n.startswith("default/web-")]
    assert len(dep_pods) == 2
    assert sum(1 for n in names if "solo-rs" in n) == 1
    assert not any("nightly" in n for n in names)
    gpu_pods = [p for p in cluster.pods if p.num_gpu]
    assert {(p.gpu_milli, p.num_gpu) for p in gpu_pods} == {(500, 1)}


def _exec_kubeconfig(tmp_path, server, plugin_body: str, exec_extra=None):
    """kubeconfig whose user authenticates via an exec credential plugin
    (a stub shell script standing in for gke-gcloud-auth-plugin & co)."""
    plugin = tmp_path / "stub-credential-plugin"
    plugin.write_text("#!/bin/sh\n" + plugin_body)
    plugin.chmod(0o755)
    p = tmp_path / "exec-kubeconfig"
    p.write_text(
        yaml.dump(
            {
                "apiVersion": "v1",
                "kind": "Config",
                "current-context": "sim",
                "clusters": [{"name": "c", "cluster": {"server": server}}],
                "users": [
                    {
                        "name": "u",
                        "user": {
                            "exec": dict(
                                {
                                    "apiVersion": (
                                        "client.authentication.k8s.io/v1"
                                    ),
                                    "command": str(plugin),
                                    "args": ["get-token"],
                                    "env": [
                                        {"name": "STUB_TOKEN_SUFFIX",
                                         "value": "-from-env"}
                                    ],
                                },
                                **(exec_extra or {}),
                            )
                        },
                    }
                ],
                "contexts": [
                    {"name": "sim", "context": {"cluster": "c", "user": "u"}}
                ],
            }
        )
    )
    return str(p)


_TOKEN_PLUGIN = """
[ "$1" = "get-token" ] || exit 2
# the client must supply the ExecCredential handshake env
echo "$KUBERNETES_EXEC_INFO" | grep -q ExecCredential || exit 3
cat <<EOF
{"apiVersion": "client.authentication.k8s.io/v1", "kind": "ExecCredential",
 "status": {"token": "exec-minted$STUB_TOKEN_SUFFIX"}}
EOF
"""


def test_exec_plugin_token(tmp_path, api_server):
    """client-go ExecCredential contract: the plugin subprocess runs with
    the configured args/env + KUBERNETES_EXEC_INFO, and its status.token
    becomes the bearer token (ref: client-go behavior behind
    utils.go:843-882)."""
    kc = _exec_kubeconfig(tmp_path, api_server, _TOKEN_PLUGIN)
    seen = {}
    orig = _Handler.do_GET

    def spy(self):
        seen["auth"] = self.headers.get("Authorization")
        return orig(self)

    _Handler.do_GET = spy
    try:
        cluster = load_cluster_from_client(kc)
    finally:
        _Handler.do_GET = orig
    assert seen["auth"] == "Bearer exec-minted-from-env"
    assert [n.name for n in cluster.nodes] == ["node-a", "node-b"]


def test_exec_plugin_clock_skew_margin(tmp_path, api_server):
    """client-go parity: a slightly-stale expirationTimestamp (clock skew
    between this host and the plugin's clock) must not abort ingestion —
    only credentials stale beyond the margin (default 30s) are fatal."""
    import datetime

    stale = (
        datetime.datetime.now(datetime.timezone.utc)
        - datetime.timedelta(seconds=10)
    ).strftime("%Y-%m-%dT%H:%M:%SZ")
    body = (
        '[ "$1" = get-token ]\n'
        'echo \'{"kind": "ExecCredential", "status": {"token": "x", '
        f'"expirationTimestamp": "{stale}"}}}}\'\n'
    )
    KubeClient(_exec_kubeconfig(tmp_path, api_server, body))  # no raise


def test_exec_plugin_failures(tmp_path, api_server):
    """Plugin failure modes surface as typed errors naming the plugin:
    non-zero exit, invalid JSON, wrong kind, and a missing binary."""
    cases = [
        ("exit 7\n", "exit 7"),
        ("echo not-json\n", "invalid JSON"),
        ('echo \'{"kind": "Secret", "status": {"token": "x"}}\'\n',
         "expected ExecCredential"),
        ('echo \'{"kind": "ExecCredential", "status": {}}\'\n',
         "neither a token"),
        # client-go rejects a response apiVersion that differs from the
        # configured exec.apiVersion (ADVICE r4)
        ('echo \'{"apiVersion": "client.authentication.k8s.io/v1beta1", '
         '"kind": "ExecCredential", "status": {"token": "x"}}\'\n',
         "apiVersion"),
        # an already-expired credential fails loudly instead of surfacing
        # later as an opaque 401 (ADVICE r4)
        ('echo \'{"kind": "ExecCredential", "status": {"token": "x", '
         '"expirationTimestamp": "2001-01-01T00:00:00Z"}}\'\n',
         "expired"),
        ('echo \'{"kind": "ExecCredential", "status": {"token": "x", '
         '"expirationTimestamp": "not-a-time"}}\'\n',
         "unparseable"),
    ]
    for body, match in cases:
        kc = _exec_kubeconfig(tmp_path, api_server, '[ "$1" = get-token ]\n' + body)
        with pytest.raises(KubeClientError, match=match):
            KubeClient(kc)
    kc = _exec_kubeconfig(tmp_path, api_server, "exit 0\n")
    import os

    # missing exec bit -> typed error, not a raw PermissionError
    (tmp_path / "stub-credential-plugin").chmod(0o644)
    with pytest.raises(KubeClientError, match="not runnable"):
        KubeClient(kc)
    os.unlink(tmp_path / "stub-credential-plugin")
    with pytest.raises(KubeClientError, match="not runnable"):
        KubeClient(kc)


def test_exec_plugin_cluster_info_and_env_edges(tmp_path, api_server):
    """provideClusterInfo puts spec.cluster in the handshake; falsy env
    values (0/false) pass through as strings, only null means empty; a
    cert without its key is a typed error."""
    body = """
echo "$KUBERNETES_EXEC_INFO" | grep -q '"server"' || exit 4
[ "$ZERO_VAL" = "0" ] || exit 5
[ "$NULL_VAL" = "" ] || exit 6
cat <<EOF
{"apiVersion": "client.authentication.k8s.io/v1", "kind": "ExecCredential",
 "status": {"token": "cluster-info-token"}}
EOF
"""
    kc = _exec_kubeconfig(
        tmp_path, api_server, body,
        exec_extra={
            "provideClusterInfo": True,
            "env": [{"name": "ZERO_VAL", "value": 0},
                    {"name": "NULL_VAL", "value": None}],
        },
    )
    client = KubeClient(kc)
    assert client._headers["Authorization"] == "Bearer cluster-info-token"

    half = (
        'echo \'{"kind": "ExecCredential", "status": '
        '{"token": "t", "clientCertificateData": "PEM"}}\'\n'
    )
    kc = _exec_kubeconfig(tmp_path, api_server, half)
    with pytest.raises(KubeClientError, match="one half"):
        KubeClient(kc)


def test_auth_provider_still_guided(tmp_path, api_server):
    """Legacy auth-provider users (no external contract) still get the
    guidance error rather than an opaque 401."""
    p = tmp_path / "ap-kubeconfig"
    p.write_text(
        yaml.dump(
            {
                "apiVersion": "v1",
                "kind": "Config",
                "current-context": "sim",
                "clusters": [{"name": "c", "cluster": {"server": api_server}}],
                "users": [
                    {"name": "u",
                     "user": {"auth-provider": {"name": "gcp"}}}
                ],
                "contexts": [
                    {"name": "sim", "context": {"cluster": "c", "user": "u"}}
                ],
            }
        )
    )
    with pytest.raises(KubeClientError, match="auth-provider"):
        KubeClient(str(p))


def test_client_auth_header(tmp_path, api_server):
    """The bearer token from the kubeconfig must reach the wire."""
    seen = {}
    orig = _Handler.do_GET

    def spy(self):
        seen["auth"] = self.headers.get("Authorization")
        return orig(self)

    _Handler.do_GET = spy
    try:
        KubeClient(_kubeconfig(tmp_path, api_server)).get("/api/v1/nodes")
    finally:
        _Handler.do_GET = orig
    assert seen["auth"] == "Bearer secret-token"


def test_client_unreachable_server(tmp_path):
    kc = _kubeconfig(tmp_path, "http://127.0.0.1:1")
    with pytest.raises(KubeClientError, match="cannot reach"):
        load_cluster_from_client(kc)


def test_applier_routes_kubeconfig_to_client(tmp_path, api_server):
    """spec.cluster.kubeConfig pointing at a kubeconfig credential drives
    the live-client ingestion end-to-end through the Applier (the
    reference's kubeConfig mode, apply.go:146-156)."""
    import io

    from tpusim.apply import Applier, ApplyOptions

    kc = _kubeconfig(tmp_path, api_server)
    cr = {
        "apiVersion": "simon/v1alpha1",
        "kind": "Config",
        "metadata": {"name": "live"},
        "spec": {"cluster": {"kubeConfig": kc}},
    }
    cc = tmp_path / "cc.yaml"
    cc.write_text(yaml.dump(cr))
    out = io.StringIO()
    Applier(
        ApplyOptions(simon_config=str(cc), extended_resources=["gpu"])
    ).run(out=out)
    assert "unscheduled pods" in out.getvalue()


def test_client_rejects_exec_plugin_kubeconfig(tmp_path):
    """GKE/EKS-style exec credential plugins must fail with guidance, not
    an opaque unauthenticated 401."""
    p = tmp_path / "kubeconfig"
    p.write_text(
        yaml.dump(
            {
                "apiVersion": "v1",
                "kind": "Config",
                "current-context": "c",
                "clusters": [
                    {"name": "c", "cluster": {"server": "http://x"}}
                ],
                "users": [
                    {
                        "name": "u",
                        "user": {
                            "exec": {"command": "gke-gcloud-auth-plugin"}
                        },
                    }
                ],
                "contexts": [
                    {"name": "c", "context": {"cluster": "c", "user": "u"}}
                ],
            }
        )
    )
    with pytest.raises(KubeClientError, match="credential plugin"):
        KubeClient(str(p))


def test_client_cleans_up_credential_material(tmp_path, api_server):
    """Inline CA/key material decoded to temp files must not outlive the
    client on disk."""
    import base64
    import gc
    import os

    p = tmp_path / "kubeconfig"
    p.write_text(
        yaml.dump(
            {
                "apiVersion": "v1",
                "kind": "Config",
                "current-context": "c",
                "clusters": [
                    {
                        "name": "c",
                        "cluster": {
                            "server": api_server,
                            # http server: CA never loaded, but the https
                            # branch materializer is what we exercise below
                        },
                    }
                ],
                "users": [{"name": "u", "user": {"token": "t"}}],
                "contexts": [
                    {"name": "c", "context": {"cluster": "c", "user": "u"}}
                ],
            }
        )
    )
    client = KubeClient(str(p))
    fake = base64.b64encode(b"not-a-real-key").decode()
    path = client._materialize(fake, None)
    assert os.path.isfile(path)
    del client
    gc.collect()
    assert not os.path.exists(path)


# ---- transient-failure retries (ISSUE 2 satellite) ----

_FLAKY = {"failures_left": 0, "status": 500, "retry_after": None, "hits": 0}


class _FlakyHandler(BaseHTTPRequestHandler):
    """Fails the first N GETs with a configurable status, then serves an
    empty node list — the recorded shape of a flaky LB hop."""

    def do_GET(self):
        _FLAKY["hits"] += 1
        if _FLAKY["failures_left"] > 0:
            _FLAKY["failures_left"] -= 1
            self.send_response(_FLAKY["status"])
            if _FLAKY["retry_after"] is not None:
                self.send_header("Retry-After", str(_FLAKY["retry_after"]))
            self.end_headers()
            return
        data = json.dumps({"apiVersion": "v1", "items": []}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):
        pass


@pytest.fixture()
def flaky_server():
    srv = HTTPServer(("127.0.0.1", 0), _FlakyHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    _FLAKY.update(failures_left=0, status=500, retry_after=None, hits=0)
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def _no_sleep(monkeypatch):
    import time as _time

    slept = []
    monkeypatch.setattr(_time, "sleep", lambda s: slept.append(s))
    return slept


def test_get_retries_transient_5xx(tmp_path, flaky_server, monkeypatch):
    """Two 503s then success: the default 3-attempt budget absorbs the
    flake with backoff sleeps instead of failing ingestion."""
    slept = _no_sleep(monkeypatch)
    _FLAKY.update(failures_left=2, status=503)
    client = KubeClient(_kubeconfig(tmp_path, flaky_server))
    assert client.get("/api/v1/nodes") == {"apiVersion": "v1", "items": []}
    assert _FLAKY["hits"] == 3 and len(slept) == 2
    assert slept[0] <= slept[1] <= 8.0  # capped exponential, jittered


def test_get_retry_honors_retry_after(tmp_path, flaky_server, monkeypatch):
    slept = _no_sleep(monkeypatch)
    _FLAKY.update(failures_left=1, status=429, retry_after=3)
    client = KubeClient(_kubeconfig(tmp_path, flaky_server))
    assert client.get("/api/v1/nodes")["items"] == []
    assert slept == [3.0]  # the server's delta-seconds wins over backoff


def test_get_retries_exhausted_raises(tmp_path, flaky_server, monkeypatch):
    _no_sleep(monkeypatch)
    _FLAKY.update(failures_left=99, status=500)
    client = KubeClient(_kubeconfig(tmp_path, flaky_server))
    with pytest.raises(KubeClientError, match="after 3 attempts"):
        client.get("/api/v1/nodes")
    assert _FLAKY["hits"] == 3


def test_get_retry_count_env_override(tmp_path, flaky_server, monkeypatch):
    """TPUSIM_HTTP_RETRIES=1 disables retrying entirely."""
    _no_sleep(monkeypatch)
    monkeypatch.setenv("TPUSIM_HTTP_RETRIES", "1")
    _FLAKY.update(failures_left=1, status=500)
    client = KubeClient(_kubeconfig(tmp_path, flaky_server))
    with pytest.raises(KubeClientError):
        client.get("/api/v1/nodes")
    assert _FLAKY["hits"] == 1


def test_get_does_not_retry_semantic_statuses(tmp_path, flaky_server,
                                              monkeypatch):
    """404/403 are group-version fallback answers, never retried."""
    slept = _no_sleep(monkeypatch)
    _FLAKY.update(failures_left=1, status=404)
    client = KubeClient(_kubeconfig(tmp_path, flaky_server))
    with pytest.raises(FileNotFoundError):
        client.get("/api/v1/nodes")
    _FLAKY.update(failures_left=1, status=403, hits=0)
    with pytest.raises(PermissionError):
        client.get("/api/v1/nodes")
    assert slept == [] and _FLAKY["hits"] == 1
