"""Coordinator HA, epoch fencing, fleet auth, capability routing
(ISSUE 17).

The tier-1 slice is pure host-side protocol — no device dispatch, no
HTTP servers, no child processes (~2 s):

  1. the leadership lease file: signed round-trip, torn/edited files
     skipped AND DELETED with a [Degrade] callback, reserved basename
     invisible to the job-lease reaper (scan_leases);
  2. CoordinatorState transitions: stale-lease takeover bumps the
     epoch, a live foreign lease is respected (epoch remembered for
     fencing), renew() detects a successor's newer epoch and demotes;
  3. epoch fencing through FleetService.handle: an op stamped with an
     OLDER epoch gets 409 {"stale_epoch": true, "register": true} and
     re-registration adopts the new epoch; an op stamped with a NEWER
     epoch deposes the handling coordinator on the spot (409
     {"deposed": true} + self-demotion to standby);
  4. a standby answers 503 + Retry-After on EVERY mutating endpoint,
     /jobs included, and health() reports role + epoch;
  5. duplicate completion of the same digest across an epoch bump is
     a silent dedup (the exactly-once-across-failover contract);
  6. bearer auth: all seven mutating endpoints 401 on a missing or
     forged token with one uniform body (no digest/worker existence
     leak), and token material never reaches /queue or logs;
  7. capability routing: fault-family work only goes to workers that
     declare fault-lane support, starved families are visible in
     /queue, FIFO holds within eligible work;
  8. the TPUSIM_COORD_LEASE_S / TPUSIM_COORD_SKEW_S knobs fail loudly
     naming the variable, and parse_url_list validates --join lists.

Slow (resume-smoke): the CoordKeeper thread drill — a leader whose
renewal timer dies is superseded by a watching standby in real time.
The full 3-process kill -9 failover acceptance lives in
gate.fleet_ha_smoke (`make fleet-ha-smoke`).
"""

import json
import time

import numpy as np
import pytest

from tpusim.io.kube_client import parse_url_list
from tpusim.io.trace import NodeRow, PodRow
from tpusim.svc import coord as svc_coord
from tpusim.svc import jobs as svc_jobs
from tpusim.svc import leases as svc_leases
from tpusim.svc.api import JobService
from tpusim.svc.auth import bearer_headers, check as auth_check, describe
from tpusim.svc.batcher import JobQueue
from tpusim.svc.coord import (
    COORD_LEASE_BASENAME,
    CoordinatorState,
    CoordKeeper,
    read_coord_lease,
    write_coord_lease,
)
from tpusim.svc.fleet import FleetService
from tpusim.svc.worker import TraceRef

FAM = [["FGDScore", 1000], ["BestFitScore", 500]]


def _mk_cluster(rng, n=16):
    return [
        NodeRow(f"n{i:03d}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], n))
    ]


def _mk_pods(rng, n=40):
    out = []
    for i in range(n):
        gpu = int(rng.choice([0, 1, 2]))
        milli = 1000 if gpu > 1 else int(rng.choice([0, 300, 500, 1000]))
        if gpu == 0:
            milli = 0
        out.append(
            PodRow(f"p{i:04d}", int(rng.choice([1000, 2000, 4000])), 2048,
                   gpu, milli)
        )
    return out


@pytest.fixture(scope="module")
def trace():
    rng = np.random.default_rng(3)
    nodes, pods = _mk_cluster(rng), _mk_pods(rng)
    return TraceRef(
        "default", nodes, pods, svc_jobs.trace_digest(nodes, pods)
    )


def _ha_stack(trace, tmp_path, token="", lease_s=30.0):
    """A coordinator stack with the HA plane armed: JobQueue +
    JobService + FleetService + a CoordinatorState that has taken
    leadership at epoch 1. lease_s is generous — the fast slice never
    waits out a deadline; staleness is driven with explicit `now`s."""
    queue = JobQueue(maxsize=32, lane_width=2, lease_s=5.0)
    service = JobService(queue, None, {"default": trace}, str(tmp_path))
    service.bucket = 512
    service.token = token
    fleet = FleetService(service)
    service.fleet = fleet
    coord = CoordinatorState(str(tmp_path), "c1", url="http://c1",
                             lease_s=lease_s, skew_s=0.0)
    assert coord.try_acquire()
    fleet.coord = coord
    return queue, service, fleet, coord


def _call(app, path, doc, headers=None, method="POST"):
    body = json.dumps(doc).encode() if doc is not None else b""
    resp = app.handle(method, path, body, headers)
    return resp[0], json.loads(resp[2].decode())


def _spec_doc(i=0, fault=False):
    doc = {"policies": FAM, "weights": [1000 + i, 500], "seed": 42}
    if fault:
        doc["fault"] = {"mtbf_events": 5.0, "seed": 7 + i}
    return doc


# ---------------------------------------------------------------------------
# 1. the leadership lease file
# ---------------------------------------------------------------------------


def test_coord_lease_roundtrip_and_torn_degrade(tmp_path):
    art = str(tmp_path)
    write_coord_lease(art, 3, "cA", 123, "http://x", time.time() + 5)
    doc = read_coord_lease(art)
    assert doc["epoch"] == 3 and doc["leader"] == "cA"
    assert doc["pid"] == 123 and doc["url"] == "http://x"
    assert not svc_coord.coord_lease_stale(doc, skew_s=0.0)
    assert svc_coord.coord_lease_stale(doc, now=time.time() + 10,
                                       skew_s=0.0)
    # skew margin: a just-expired lease is NOT stale under skew
    assert not svc_coord.coord_lease_stale(
        doc, now=doc["deadline_unix"] + 1.0, skew_s=2.0
    )

    # tear the file: skipped, reported, DELETED — never trusted
    path = svc_coord.coord_lease_path(art)
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: len(raw) // 2])
    skipped = []
    assert read_coord_lease(art, on_skip=lambda p, e: skipped.append(p)) \
        is None
    assert skipped == [path]
    import os
    assert not os.path.exists(path)


def test_coord_lease_invisible_to_job_lease_reaper(tmp_path):
    """coordinator.lease.json shares the *.lease.json suffix with the
    per-job files; scan_leases must neither judge nor delete it."""
    art = str(tmp_path)
    write_coord_lease(art, 1, "cA", 123, "", time.time() - 100)  # stale!
    digest = "d" * 64
    svc_leases.write_lease(art, digest, "w1", 11, time.time() + 60,
                           [digest])
    leases = svc_leases.scan_leases(art)
    assert [d for d, _ in leases] == [digest]
    assert read_coord_lease(art) is not None  # survived the scan
    assert COORD_LEASE_BASENAME == "coordinator.lease.json"


# ---------------------------------------------------------------------------
# 2. CoordinatorState transitions
# ---------------------------------------------------------------------------


def test_stale_lease_takeover_bumps_epoch(tmp_path):
    art = str(tmp_path)
    c1 = CoordinatorState(art, "c1", lease_s=10.0, skew_s=0.0)
    c2 = CoordinatorState(art, "c2", lease_s=10.0, skew_s=0.0)
    now = time.time()
    assert c1.try_acquire(now) and c1.epoch == 1 and c1.role == "leader"

    # live foreign lease: c2 stays standby but REMEMBERS the epoch
    assert not c2.try_acquire(now + 1)
    assert c2.role == "standby" and c2.epoch == 1

    # the leader stops renewing; past deadline + skew, c2 takes over
    assert c2.try_acquire(now + 10.0 + 0.1)
    assert c2.role == "leader" and c2.epoch == 2 and c2.takeovers == 1
    assert read_coord_lease(art)["leader"] == "c2"

    # the resurrected c1 sees the newer on-disk epoch and demotes
    assert not c1.renew(now + 11)
    assert c1.role == "standby" and c1.demotions == 1
    assert c1.epoch == 1  # it learns epoch 2 from the next fenced op

    # re-acquiring while c2's lease is live fails; after release, wins
    assert not c1.try_acquire(now + 12)
    c2.release()
    assert read_coord_lease(art) is None
    assert c1.try_acquire(now + 13)
    assert c1.epoch == 3  # max(seen 2, ours 1) + 1


def test_leader_renew_in_place_and_release_respects_successor(tmp_path):
    art = str(tmp_path)
    c1 = CoordinatorState(art, "c1", lease_s=10.0, skew_s=0.0)
    now = time.time()
    assert c1.try_acquire(now)
    d0 = read_coord_lease(art)["deadline_unix"]
    assert c1.renew(now + 3)
    assert read_coord_lease(art)["deadline_unix"] > d0
    # try_acquire while already leading is a renew, not an epoch bump
    assert c1.try_acquire(now + 4) and c1.epoch == 1

    # a successor stakes epoch 2; c1.release() must NOT delete it
    write_coord_lease(art, 2, "c2", 99, "", now + 100)
    c1.release()
    assert read_coord_lease(art)["leader"] == "c2"


# ---------------------------------------------------------------------------
# 3. epoch fencing through the fleet protocol
# ---------------------------------------------------------------------------


def test_stale_epoch_op_409_and_reregister_adopts_epoch(trace, tmp_path):
    queue, service, fleet, coord = _ha_stack(trace, tmp_path)
    code, reg = _call(fleet, "/workers/register",
                      {"worker": "", "pid": 11, "host": "h",
                       "caps": {"backend": "cpu", "devices": 1}})
    assert code == 200 and reg["epoch"] == 1
    w1 = reg["worker"]

    # a failover happened elsewhere: our coordinator is now at epoch 3
    coord.note_epoch(2)  # deposes c1 …
    assert coord.role == "standby"
    assert coord.try_acquire()  # … and c1 wins leadership back
    assert coord.epoch == 3

    # the worker still stamps its registration-time epoch → fenced,
    # told to re-register. Fencing runs BEFORE worker lookup: even an
    # unknown sender learns only the epoch, nothing about the registry.
    code, doc = _call(fleet, "/workers/claim", {"worker": w1, "epoch": 1})
    assert code == 409 and doc["stale_epoch"] and doc["register"]
    assert doc["epoch"] == 3
    code, doc = _call(fleet, "/workers/claim",
                      {"worker": "ghost", "epoch": 1})
    assert code == 409 and doc["stale_epoch"]

    # re-registration hands back the current epoch; ops flow again
    code, reg2 = _call(fleet, "/workers/register",
                       {"worker": w1, "pid": 11})
    assert code == 200 and reg2["epoch"] == 3
    code, claim = _call(fleet, "/workers/claim",
                        {"worker": w1, "epoch": 3})
    assert code == 200 and claim["epoch"] == 3

    # a malformed stamp is a 400, not a crash or a silent pass
    code, doc = _call(fleet, "/workers/claim",
                      {"worker": w1, "epoch": "banana"})
    assert code == 400
    # an UNSTAMPED op (pre-HA worker) passes the fence untouched
    code, _ = _call(fleet, "/workers/claim", {"worker": w1})
    assert code == 200


def test_newer_epoch_op_deposes_handling_coordinator(trace, tmp_path,
                                                     capsys):
    queue, service, fleet, coord = _ha_stack(trace, tmp_path)
    _call(fleet, "/workers/register", {"worker": "w1", "pid": 11})

    # a worker registered with a NEWER leader talks to the deposed one:
    # the op itself is the proof — demote on the spot, answer 409
    code, doc = _call(fleet, "/workers/claim", {"worker": "w1", "epoch": 5})
    assert code == 409 and doc["deposed"] and doc["epoch"] == 5
    assert coord.role == "standby" and coord.epoch == 5
    assert "DEPOSED" in capsys.readouterr().err

    # from now on EVERY mutating endpoint is a 503 with Retry-After —
    # the demoted leader cannot corrupt shared state
    for path, body in [
        ("/workers/claim", {"worker": "w1", "epoch": 5}),
        ("/workers/register", {"worker": "", "pid": 1}),
        ("/workers/renew", {"worker": "w1", "digests": []}),
        ("/workers/complete", {"worker": "w1", "done": []}),
        ("/leases", {"op": "write", "digest": "d" * 64}),
        ("/results/" + "d" * 64, {}),
    ]:
        resp = fleet.handle("POST", path, json.dumps(body).encode(), None)
        assert resp[0] == 503, path
        assert len(resp) == 4 and resp[3] == {"Retry-After": "2"}, path
        assert json.loads(resp[2].decode())["role"] == "standby"
    code, doc = _call(service, "/jobs", _spec_doc())
    assert code == 503 and doc["role"] == "standby"

    # reads still answer (the operator needs /queue to see WHY)
    code, q = _call(service, "/queue", None, method="GET")
    assert code == 200 and q["role"] == "standby" and q["epoch"] == 5

    # health: a standby is healthy by existing, and says so
    ok, extra = fleet.health()
    assert ok and extra["role"] == "standby" and extra["epoch"] == 5


def test_duplicate_completion_across_epochs_dedups(trace, tmp_path):
    """Exactly-once across failover: the same digest completed under
    epoch 1 and again (by the re-registered worker, after adoption)
    under epoch 2 is acked once and deduped once — digests pin
    trajectories, result writes are atomic replaces."""
    queue, service, fleet, coord = _ha_stack(trace, tmp_path)
    art = str(tmp_path)
    _call(fleet, "/workers/register", {"worker": "w1", "pid": 11})
    service.submit_payload(_spec_doc(0))
    code, claim = _call(fleet, "/workers/claim",
                        {"worker": "w1", "epoch": 1})
    [jd] = claim["jobs"]
    svc_jobs.write_result(art, jd["digest"], {"placed": 1})
    code, comp = _call(fleet, "/workers/complete",
                       {"worker": "w1", "epoch": 1,
                        "done": [jd["digest"]]})
    assert code == 200 and comp["acked"] == 1 and comp["dup"] == 0

    # failover: a successor leads at epoch 2, adopts the artifact dir
    c2 = CoordinatorState(art, "c2", lease_s=30.0, skew_s=0.0)
    assert c2.try_acquire(time.time() + 100)  # c1's lease judged stale
    assert c2.epoch == 2
    fleet.coord = c2  # the same queue state, now fenced at epoch 2

    # the worker re-registers and re-sends the completion it never got
    # an ack for (its POST raced the old leader's death)
    code, reg = _call(fleet, "/workers/register", {"worker": "w1",
                                                   "pid": 11})
    assert reg["epoch"] == 2
    code, comp2 = _call(fleet, "/workers/complete",
                        {"worker": "w1", "epoch": 2,
                         "done": [jd["digest"]]})
    assert code == 200 and comp2["acked"] == 0 and comp2["dup"] == 1
    st = queue.stats()
    assert st["done"] == 1


# ---------------------------------------------------------------------------
# 4. bearer auth on the mutating plane
# ---------------------------------------------------------------------------


def test_auth_401_on_every_mutating_endpoint(trace, tmp_path):
    token = "s3cret-token-0123456789"
    queue, service, fleet, coord = _ha_stack(trace, tmp_path, token=token)
    digest = "d" * 64
    mutating = [
        (fleet, "/workers/register", {"worker": "", "pid": 1}),
        (fleet, "/workers/claim", {"worker": "w1", "epoch": 1}),
        (fleet, "/workers/renew", {"worker": "w1", "digests": []}),
        (fleet, "/workers/complete", {"worker": "w1", "done": []}),
        (fleet, "/leases", {"op": "write", "digest": digest}),
        (fleet, "/results/" + digest, {}),
        (service, "/jobs", _spec_doc()),
    ]
    for headers in (None, {}, {"Authorization": "Bearer wrong"},
                    {"Authorization": token}):  # missing Bearer prefix
        for app, path, body in mutating:
            code, doc = _call(app, path, body, headers=headers)
            assert code == 401, (path, headers)
            # ONE uniform body: a 401 never reveals whether the digest
            # or worker exists, and never echoes the expected token
            assert doc == {"error": "missing or invalid bearer token"}

    # the real token passes; /queue shows armed-or-not, NEVER material
    ok = bearer_headers(token)
    code, reg = _call(fleet, "/workers/register",
                      {"worker": "", "pid": 1}, headers=ok)
    assert code == 200
    code, q = _call(service, "/queue", None, method="GET")
    assert code == 200 and q["auth"] == describe(token)
    assert token not in json.dumps(q)
    assert q["auth"].startswith("enabled")

    # reads stay open (health probes and dashboards don't carry tokens)
    code, _ = _call(fleet, "/workers", None, method="GET")
    assert code == 200

    # check() semantics: empty token disables, compare is exact
    assert auth_check({}, "")
    assert not auth_check({}, token)
    assert auth_check({"Authorization": "Bearer " + token}, token)
    assert not auth_check({"Authorization": "Bearer " + token + "x"},
                          token)
    assert bearer_headers("") == {}
    assert describe("") == "disabled"


def test_load_token_fail_loud(tmp_path, monkeypatch):
    from tpusim.svc.auth import ENV_TOKEN, load_token

    monkeypatch.delenv(ENV_TOKEN, raising=False)
    assert load_token("") == ""
    monkeypatch.setenv(ENV_TOKEN, "  env-tok  ")
    assert load_token("") == "env-tok"

    f = tmp_path / "tok.txt"
    f.write_text("file-tok\n")
    assert load_token(str(f)) == "file-tok"  # file beats env
    (tmp_path / "empty.txt").write_text("  \n")
    with pytest.raises(ValueError, match="empty"):
        load_token(str(tmp_path / "empty.txt"))
    with pytest.raises(ValueError, match="unreadable"):
        load_token(str(tmp_path / "missing.txt"))


# ---------------------------------------------------------------------------
# 5. capability routing + starvation visibility
# ---------------------------------------------------------------------------


def test_capability_routing_and_starved_family_in_queue(trace, tmp_path,
                                                        capsys):
    queue, service, fleet, coord = _ha_stack(trace, tmp_path)

    # the serve wiring installs this; replicate it here (api.start_job_
    # server owns the real install)
    def _needs(spec):
        return {"fault": bool(spec.fault), "nodes": len(trace.nodes),
                "mem_bytes": 0}
    queue.family_needs_fn = _needs

    _call(fleet, "/workers/register",
          {"worker": "wplain", "pid": 1,
           "caps": {"backend": "cpu", "devices": 1,
                    "fault_lanes": False}})
    service.submit_payload(_spec_doc(0, fault=True))
    service.submit_payload(_spec_doc(1))

    # the incapable worker claims PAST the fault job (FIFO within
    # eligible work) and the starved family turns loud + visible
    code, claim = _call(fleet, "/workers/claim",
                        {"worker": "wplain", "epoch": 1})
    got = [bool(svc_jobs.validate_job(j["spec"]).fault)
           for j in claim["jobs"]]
    assert got == [False]
    code, q = _call(service, "/queue", None, method="GET")
    assert len(q["starved_families"]) == 1
    assert "STARVED" in capsys.readouterr().err
    # a second claim finds ONLY work it cannot serve: empty + a tick
    code, claim = _call(fleet, "/workers/claim",
                        {"worker": "wplain", "epoch": 1})
    assert claim["jobs"] == []
    assert queue.stats()["starved_claims"] >= 1

    # a capable worker joins: the fault job flows, starvation clears
    _call(fleet, "/workers/register",
          {"worker": "wfault", "pid": 2,
           "caps": {"backend": "cpu", "devices": 1,
                    "fault_lanes": True}})
    code, claim2 = _call(fleet, "/workers/claim",
                         {"worker": "wfault", "epoch": 1})
    assert [bool(svc_jobs.validate_job(j["spec"]).fault)
            for j in claim2["jobs"]] == [True]
    code, q = _call(service, "/queue", None, method="GET")
    assert q["starved_families"] == []


def test_eligible_caps_matrix(trace, tmp_path):
    queue = JobQueue(maxsize=8, lane_width=1)
    spec_plain = svc_jobs.validate_job(_spec_doc(0))
    spec_fault = svc_jobs.validate_job(_spec_doc(1, fault=True))
    queue.family_needs_fn = lambda s: {
        "fault": bool(s.fault), "nodes": 500, "mem_bytes": 1 << 30
    }
    # no caps (pre-ISSUE-17 worker / in-process) = unrestricted
    assert queue.eligible(spec_fault, None)
    assert queue.eligible(spec_fault, {})
    assert not queue.eligible(spec_fault, {"fault_lanes": False})
    assert queue.eligible(spec_plain, {"fault_lanes": False})
    # max_nodes / memory thresholds; 0 = undeclared = unlimited
    assert not queue.eligible(spec_plain, {"max_nodes": 100})
    assert queue.eligible(spec_plain, {"max_nodes": 500})
    assert queue.eligible(spec_plain, {"max_nodes": 0})
    assert not queue.eligible(spec_plain, {"memory_bytes": 1 << 20})
    assert queue.eligible(spec_plain, {"memory_bytes": 1 << 31})
    # a broken needs fn must not wedge claims: falls back to spec.fault
    queue.family_needs_fn = lambda s: 1 / 0
    assert queue.eligible(spec_plain, {"max_nodes": 1})
    assert not queue.eligible(spec_fault, {"fault_lanes": False})


# ---------------------------------------------------------------------------
# 6. knobs + URL lists
# ---------------------------------------------------------------------------


def test_coord_env_knobs_fail_loud(monkeypatch):
    monkeypatch.delenv("TPUSIM_COORD_LEASE_S", raising=False)
    monkeypatch.delenv("TPUSIM_COORD_SKEW_S", raising=False)
    assert svc_coord.coord_lease_s() == svc_coord.DEFAULT_COORD_LEASE_S
    assert svc_coord.coord_skew_s() == 2.0

    monkeypatch.setenv("TPUSIM_COORD_LEASE_S", "fast")
    with pytest.raises(ValueError, match="TPUSIM_COORD_LEASE_S"):
        svc_coord.coord_lease_s()
    monkeypatch.setenv("TPUSIM_COORD_LEASE_S", "0")
    with pytest.raises(ValueError, match="TPUSIM_COORD_LEASE_S"):
        svc_coord.coord_lease_s()
    monkeypatch.setenv("TPUSIM_COORD_LEASE_S", "1.5")
    assert svc_coord.coord_lease_s() == 1.5

    monkeypatch.setenv("TPUSIM_COORD_SKEW_S", "-1")
    with pytest.raises(ValueError, match="TPUSIM_COORD_SKEW_S"):
        svc_coord.coord_skew_s()
    monkeypatch.setenv("TPUSIM_COORD_SKEW_S", "0.5")
    assert svc_coord.coord_skew_s() == 0.5


def test_parse_url_list():
    assert parse_url_list("http://a:1") == ["http://a:1"]
    assert parse_url_list("http://a:1/, http://b:2 ,http://a:1") == \
        ["http://a:1", "http://b:2"]
    assert parse_url_list(["http://a:1", "http://b:2/"]) == \
        ["http://a:1", "http://b:2"]
    with pytest.raises(ValueError, match="no coordinator URLs"):
        parse_url_list(" , ,")
    with pytest.raises(ValueError, match="no coordinator URLs"):
        parse_url_list("")


# ---------------------------------------------------------------------------
# 7. the renewal timer drill (threads + real sleeps -> slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_keeper_crash_takeover_in_real_time(tmp_path):
    """c1 leads with a live CoordKeeper; the keeper dies (a wedged
    leader); the watching c2 takes over one lease + skew later; c1's
    next renew self-demotes and fires on_deposed."""
    art = str(tmp_path)
    c1 = CoordinatorState(art, "c1", lease_s=0.3, skew_s=0.0)
    c2 = CoordinatorState(art, "c2", lease_s=0.3, skew_s=0.0)
    assert c1.try_acquire()
    keeper = CoordKeeper(c1).start()
    time.sleep(0.5)  # several renewals pass; c2 cannot take over
    assert not c2.try_acquire()
    keeper.stop()  # the "crash": renewals stop, the lease goes stale

    deadline = time.time() + 10.0
    while time.time() < deadline:
        if c2.try_acquire():
            break
        time.sleep(0.05)
    assert c2.role == "leader" and c2.epoch == 2

    deposed = []
    k1 = CoordKeeper(c1, on_deposed=lambda: deposed.append(1))
    c1.role = "leader"  # simulate the zombie believing it still leads
    k1.start()
    k2 = CoordKeeper(c2).start()
    deadline = time.time() + 10.0
    while time.time() < deadline and not deposed:
        time.sleep(0.05)
    k1.stop()
    k2.stop(release=True)
    assert deposed and c1.role == "standby"
    assert read_coord_lease(art) is None  # graceful stop released it
