"""The learned-scoring lane (tpusim.learn; ISSUE 9).

Pins the lane's contracts:

  1. optimizers: seeded ES/CMA are bit-reproducible (same seed -> same
     trajectory; state_dict round-trip continues identically) and
     converge on a synthetic separable objective in <= 20 generations;
  2. the i32 operand bridge: projection rounds/clips onto the engines'
     weight space, integer collisions dedup before rollout;
  3. the objective: scalarized exactly as documented, term vocabulary
     identical between a local SweepLane and a service result document;
  4. the loop: digest-signed tuning log, byte-identical re-runs under a
     fixed seed, resume-from-log equivalence (kill at generation k,
     resume -> the uninterrupted file's bytes), zero recompiles after
     generation 1 on the local backend;
  5. local-vs-remote: the same tuning run against a `serve --jobs`
     service reproduces the local log bit-identically (slow — HTTP +
     worker thread);
  6. the openb acceptance (slow, `make resume-smoke`): `tpusim tune` on
     an openb prefix strictly improves the scalarized objective over
     the paper-default weights on the held-out trace suffix.

The fast slice stays on a tiny synthetic cluster sharing one compiled
family (~<= 15 s — the tier-1 budget); everything compile-heavy is
slow-marked into `make resume-smoke`.
"""

import json
import os

import numpy as np
import pytest

from tpusim.io.trace import NodeRow, PodRow
from tpusim.learn import (
    DiagonalCMA,
    LocalRollout,
    ObjectiveConfig,
    OpenAIES,
    TuneConfig,
    centered_ranks,
    dedup_rows,
    lane_terms,
    make_family_sim,
    project_weights,
    read_log,
    run_tune,
    scalarize,
    terms_from_result,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAM = [("FGDScore", 1000), ("BestFitScore", 500)]

TARGET = np.array([3.0, -2.0, 1.0])


def _quad(xs):
    """Separable synthetic objective (maximize; optimum = TARGET)."""
    return -np.sum((np.asarray(xs) - TARGET) ** 2, axis=-1)


def _mk_cluster(rng, n=16):
    return [
        NodeRow(f"n{i:03d}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], n))
    ]


def _mk_pods(rng, n=40):
    out = []
    for i in range(n):
        gpu = int(rng.choice([0, 1, 2]))
        milli = 1000 if gpu > 1 else int(rng.choice([0, 300, 500, 1000]))
        if gpu == 0:
            milli = 0
        out.append(
            PodRow(f"p{i:04d}", int(rng.choice([1000, 2000, 4000])), 2048,
                   gpu, milli)
        )
    return out


# ---------------------------------------------------------------------------
# 1. optimizers: reproducibility + convergence
# ---------------------------------------------------------------------------


def test_centered_ranks():
    u = centered_ranks([10.0, -5.0, 3.0, 99.0])
    assert u.min() == -0.5 and u.max() == 0.5
    assert abs(u.sum()) < 1e-12  # mean-zero (antithetic cancellation)
    # monotone-invariant: any order-preserving transform, same utilities
    assert np.array_equal(u, centered_ranks([1.0, -1.0, 0.5, 2.0]))
    assert np.array_equal(centered_ranks([7.0]), [0.0])


@pytest.mark.parametrize("make", [
    lambda: OpenAIES(np.zeros(3), sigma=0.5, lr=3.0, popsize=8, seed=5),
    lambda: DiagonalCMA(np.zeros(3), sigma=1.0, popsize=8, seed=5),
])
def test_optimizer_bit_reproducible(make):
    """Same seed -> identical trajectory; a state_dict round-trip into a
    FRESH instance continues bit-identically (the resume contract —
    generation draws are a pure function of (seed, gen))."""
    a, b = make(), make()
    for g in range(5):
        xa, xb = a.ask(g), b.ask(g)
        assert np.array_equal(xa, xb)
        a.tell(g, _quad(xa))
        b.tell(g, _quad(xb))
    assert np.array_equal(a.mean, b.mean)

    # JSON round-trip the state mid-run into a fresh optimizer
    c = make()
    c.load_state(json.loads(json.dumps(a.state_dict())))
    for g in range(5, 8):
        xa, xc = a.ask(g), c.ask(g)
        assert np.array_equal(xa, xc)
        a.tell(g, _quad(xa))
        c.tell(g, _quad(xc))
    assert np.array_equal(a.mean, c.mean)


@pytest.mark.parametrize("make", [
    lambda: OpenAIES(np.zeros(3), sigma=0.5, lr=3.0, popsize=16, seed=7),
    lambda: DiagonalCMA(np.zeros(3), sigma=1.0, popsize=12, seed=7),
])
def test_optimizer_converges_separable(make):
    """<= 20 generations to the optimum of a separable quadratic — the
    ISSUE 9 sample-efficiency bar."""
    opt = make()
    for g in range(20):
        xs = opt.ask(g)
        opt.tell(g, _quad(xs))
    assert _quad(opt.mean) > -0.25, opt.mean  # started at -14


def test_optimizer_validation():
    with pytest.raises(ValueError, match="even"):
        OpenAIES(np.zeros(2), popsize=5)
    with pytest.raises(ValueError, match=">= 4"):
        DiagonalCMA(np.zeros(2), popsize=3)
    opt = OpenAIES(np.zeros(2), popsize=4)
    with pytest.raises(ValueError, match="shape"):
        opt.tell(0, [1.0, 2.0])
    with pytest.raises(ValueError, match="algo"):
        opt.load_state({"algo": "cma"})


# ---------------------------------------------------------------------------
# 2. integer projection + dedup
# ---------------------------------------------------------------------------


def test_project_weights():
    out = project_weights([[999.6, -3.0], [4500.2, 0.4]], lo=0, hi=4000)
    assert out.dtype == np.int32
    assert out.tolist() == [[1000, 0], [4000, 0]]
    with pytest.raises(ValueError, match="lo < hi"):
        project_weights([[1.0]], lo=5, hi=5)


def test_dedup_rows():
    rows = np.asarray([[10, 20], [30, 40], [10, 20], [10, 20]], np.int32)
    uniq, where = dedup_rows(rows)
    assert uniq == [(10, 20), (30, 40)]  # first-seen order
    assert where == [0, 1, 0, 0]
    # scattering objectives back covers every candidate
    objs_u = [1.5, -2.0]
    assert [objs_u[w] for w in where] == [1.5, -2.0, 1.5, 1.5]


# ---------------------------------------------------------------------------
# 3. the objective
# ---------------------------------------------------------------------------


def test_scalarize():
    terms = {
        "gpu_alloc_pct": 80.0, "frag_gpu_milli": 5000.0,
        "gpu_total_milli": 100_000, "unscheduled": 2, "pods": 40,
    }
    # 1*80 - 1*(100*5000/100000) - 1*(100*2/40) = 80 - 5 - 5
    assert scalarize(terms) == pytest.approx(70.0)
    assert scalarize(
        terms, ObjectiveConfig(w_alloc=2.0, w_frag=0.5, w_unsched=0.0)
    ) == pytest.approx(160.0 - 2.5)


def test_terms_vocabulary_local_vs_remote():
    """terms_from_result over a service result doc yields EXACTLY the
    dict lane_terms builds locally — key set, value types, values (the
    log bit-identity reduces to this plus the sweep bit-identity
    test_svc already pins)."""
    doc = {
        "weights": [1000, 500], "seed": 42, "events": 80, "pods": 40,
        "placed": 38, "failed": 2, "unscheduled": 2,
        "gpu_total_milli": 64000, "gpu_alloc_pct": 81.25,
        "frag_gpu_milli": 1234.5, "placements_sha256": "ab" * 32,
        # extra service-side keys are ignored, not copied
        "job": "deadbeef", "placed_node": [0] * 40,
    }
    terms = terms_from_result(doc)
    assert set(terms) == {
        "weights", "seed", "events", "pods", "placed", "failed",
        "unscheduled", "disrupted", "evicted", "gpu_total_milli",
        "gpu_alloc_pct", "frag_gpu_milli", "placements_sha256",
    }
    # pre-chaos result docs (no disruption keys) read back as fault-free
    assert terms["disrupted"] == 0 and terms["evicted"] == 0
    assert json.dumps(terms, sort_keys=True) == json.dumps(
        {k: doc.get(k, 0) for k in terms}, sort_keys=True
    )


# ---------------------------------------------------------------------------
# 4. the loop on the local backend (device; the tier-1 slice's one
#    compiled family)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def synth():
    rng = np.random.default_rng(3)
    return _mk_cluster(rng), _mk_pods(rng)


CFG = dict(algo="es", generations=3, popsize=4, sigma=300.0, lr=400.0,
           seed=9)


@pytest.mark.slow  # tier-1 trim, ISSUE 16: rides resume-smoke
def test_local_tune_log_resume_and_zero_recompile(synth, tmp_path):
    """One small tuning run pins four contracts at once (one compile
    family — the tier-1 budget): (a) the signed log round-trips with
    one record per generation; (b) a same-seed re-run reproduces it
    byte-identically; (c) killing after generation 1 and resuming
    yields the SAME bytes as the uninterrupted run; (d) the whole run
    dispatched ONE compiled sweep executable."""
    nodes, pods = synth
    cfg = TuneConfig(**CFG)

    sim = make_family_sim(nodes, pods, FAM)
    backend = LocalRollout(sim, width=cfg.popsize)
    log_a = str(tmp_path / "a.jsonl")
    result = run_tune(backend, FAM, cfg, log_a)

    # (a) signed log: one record per generation, state present, the
    # best-so-far is monotone
    header, records = read_log(log_a)
    assert header["config"]["algo"] == "es"
    assert [r["gen"] for r in records] == [0, 1, 2]
    bests = [r["best"]["objective"] for r in records]
    assert bests == sorted(bests)
    assert result.best_objective == bests[-1]
    for r in records:
        assert len(r["population"]) == cfg.popsize
        assert len(r["terms"]) == len(r["unique"])
        assert r["state"]["algo"] == "es"

    # (b) byte-identical re-run (same backend: the jaxpr is cached, the
    # trajectory is seed-determined)
    log_b = str(tmp_path / "b.jsonl")
    run_tune(backend, FAM, cfg, log_b)
    with open(log_a, "rb") as f:
        bytes_a = f.read()
    with open(log_b, "rb") as f:
        assert f.read() == bytes_a

    # (c) kill/resume equivalence: 2 generations, then resume to 3
    log_c = str(tmp_path / "c.jsonl")
    run_tune(backend, FAM, TuneConfig(**{**CFG, "generations": 2}), log_c)
    run_tune(backend, FAM, cfg, log_c, resume=True)
    with open(log_c, "rb") as f:
        assert f.read() == bytes_a

    # resume under a different trajectory config fails loudly
    with pytest.raises(ValueError, match="different config"):
        run_tune(
            backend, FAM, TuneConfig(**{**CFG, "seed": 10}), log_c,
            resume=True,
        )

    # (d) zero recompiles: every generation of every run above rode one
    # compiled sweep executable
    assert backend.executables() == 1


def test_tuning_curve_emitter(synth, tmp_path):
    """The obs tuning-curve emitter renders straight from log records."""
    from tpusim.obs.emitters import format_tuning_curve, tuning_curve_series

    nodes, pods = synth
    cfg = TuneConfig(**CFG)
    sim = make_family_sim(nodes, pods, FAM)
    backend = LocalRollout(sim, width=cfg.popsize)
    log = str(tmp_path / "t.jsonl")
    run_tune(backend, FAM, cfg, log)
    _, records = read_log(log)

    tracks = tuning_curve_series(records)
    assert tracks["tune_gen"] == [0, 1, 2]
    assert len(tracks["tune_best"]) == 3
    assert tracks["tune_best"] == sorted(tracks["tune_best"])
    text = format_tuning_curve(records)
    assert "3 generations" in text and "best" in text
    assert format_tuning_curve([]) == "[tune] no generations recorded"


def test_lane_terms_match_backend(synth):
    """LocalRollout's term dicts are lane_terms of the sweep lanes, and
    carry the unscheduled/gpu_total fields the driver now exposes."""
    nodes, pods = synth
    sim = make_family_sim(nodes, pods, FAM)
    backend = LocalRollout(sim, width=2)
    terms = backend.rollout([(1000, 500), (500, 1000)], seed=42)
    assert len(terms) == 2
    lanes = sim.run_sweep(
        np.asarray([[1000, 500], [500, 1000]], np.int32), seeds=[42, 42]
    )
    for t, lane in zip(terms, lanes):
        assert t == lane_terms(lane)
        assert t["unscheduled"] == lane.unscheduled
        assert t["gpu_total_milli"] == sim.node_total_milli_gpu
        assert t["pods"] == len(pods)
    # a dedup-shrunk generation must not exceed the backend width
    with pytest.raises(ValueError, match="exceed the backend width"):
        backend.rollout([(1, 1), (2, 2), (3, 3)], seed=42)


# ---------------------------------------------------------------------------
# 5. local vs remote: identical tuning logs (slow — HTTP + worker thread)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_local_vs_remote_log_identical(synth, tmp_path):
    """The remote backend (a real `serve --jobs` service over HTTP)
    reproduces the local backend's tuning log bit-identically under the
    same seed — the ISSUE 9 acceptance contract. CMA here so both
    optimizer families cross a real rollout path somewhere."""
    from tpusim.learn import RemoteRollout
    from tpusim.svc import jobs as svc_jobs
    from tpusim.svc.api import start_job_server
    from tpusim.svc.worker import TraceRef

    nodes, pods = synth
    cfg = TuneConfig(algo="cma", generations=3, popsize=4, sigma=300.0,
                     seed=9)

    sim = make_family_sim(nodes, pods, FAM)
    local_log = str(tmp_path / "local.jsonl")
    run_tune(LocalRollout(sim, width=cfg.popsize), FAM, cfg, local_log)

    trace = TraceRef(
        "default", nodes, pods, svc_jobs.trace_digest(nodes, pods)
    )
    art = tmp_path / "art"
    art.mkdir()
    srv, service, worker = start_job_server(
        str(art), {"default": trace}, listen=":0",
        lane_width=cfg.popsize, queue_size=16,
    )
    try:
        remote_log = str(tmp_path / "remote.jsonl")
        run_tune(
            RemoteRollout(srv.url, FAM), FAM, cfg, remote_log
        )
    finally:
        worker.stop()
        srv.stop()
    with open(local_log, "rb") as fa, open(remote_log, "rb") as fb:
        assert fa.read() == fb.read()


# ---------------------------------------------------------------------------
# 6. openb acceptance (slow; `make resume-smoke`)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_openb_tune_acceptance(tmp_path):
    """ISSUE 9 acceptance on an openb prefix: tuning on the train
    prefix strictly improves the scalarized objective over the
    paper-default weights on the HELD-OUT trace suffix, with zero
    recompiles after generation 1 and a signed resumable log."""
    from tpusim.io.trace import load_node_csv, load_pod_csv
    from tpusim.learn import format_holdout_report, holdout_report

    nodes = load_node_csv(
        os.path.join(REPO, "data/csv/openb_node_list_gpu_node.csv")
    )
    pods = load_pod_csv(
        os.path.join(REPO, "data/csv/openb_pod_list_default.csv")
    )[:400]
    n_train = len(pods) - len(pods) // 5  # the CLI's --holdout 0.2 split
    train, held = pods[:n_train], pods[n_train:]

    cfg = TuneConfig(algo="es", generations=4, popsize=6, sigma=300.0,
                     lr=400.0, seed=1)
    sim = make_family_sim(nodes, train, FAM)
    backend = LocalRollout(sim, width=cfg.popsize)
    log = str(tmp_path / "openb.jsonl")

    # generation 1 alone, then kill/resume to 4: zero recompiles after
    # generation 1 (the wrapper's executable count is a process-global
    # jit cache, so the contract is STABILITY, not an absolute count —
    # earlier tests in this process may have compiled other shapes)
    run_tune(backend, FAM, TuneConfig(**{**cfg.__dict__,
                                         "generations": 1}), log)
    execs_after_g1 = backend.executables()
    result = run_tune(backend, FAM, cfg, log, resume=True)
    assert backend.executables() == execs_after_g1

    # the resumed log is byte-identical to an uninterrupted run's
    log_b = str(tmp_path / "openb_b.jsonl")
    run_tune(backend, FAM, cfg, log_b)
    with open(log, "rb") as fa, open(log_b, "rb") as fb:
        assert fa.read() == fb.read()

    # held-out suffix: tuned strictly beats the paper-default weights
    eval_sim = make_family_sim(nodes, held, FAM)
    report = holdout_report(
        eval_sim, FAM, result.best_weights, eval_seed=cfg.eval_seed
    )
    text = format_holdout_report(report, FAM)
    assert report["improvement"] > 0, text
    assert "tuned beats default" in text
