"""Config-axis sweeps + weight-operand engines (ISSUE 6).

The per-policy weight vector is a traced i32[num_pol] operand
(sim.step.resolve_weights) threaded through all four engines, and
driver.schedule_pods_sweep vmaps one compiled replay over a [B, num_pol]
weight matrix plus per-config seeds. These tests pin:

  1. cross-engine bit-identity under a NON-static weight operand —
     sequential / flat table / blocked table / shard_map all agree for
     every weight vector of a grid, including RandomScore's key split
     and minmax/pwr normalize mixes (the blocked summaries bt/br/bn are
     built in-scan FROM the operand, so this is the blocked-summary
     drift check under traced weights);
  2. sweep lanes == standalone runs with those weights baked into the
     config, per engine path (table, sequential) and per-lane seed;
  3. one jaxpr per job family: a weight change reuses the compiled
     engine (replayers differing only in weights share `replay.engine`,
     and a second sweep over a different grid adds no executable);
  4. the digest vocabulary: weights are a RUN input (the run digest
     moves when they move, so a checkpointed carry — whose blocked
     summaries embed the weights — can never be resumed under different
     weights) but NOT a table-cache input (one build serves every
     weight vector of the family);
  5. the openb acceptance (slow, `make resume-smoke` / `make
     sweep-smoke`): a B=16 sweep over the openb prefix runs under
     exactly one scan span with zero recompiles on a weight change,
     each sampled lane bit-identical to its standalone baked-weight
     run, and a bounded marginal per-config cost (strict 1/5 on
     accelerator backends; on CPU vmap only strips per-op dispatch
     overhead, so the honest bound is "cheaper than a standalone warm
     replay" — ENGINES.md Round 11 quantifies both).
"""

import io
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import random_cluster, random_pods
from tests.test_table_engine import _events_with_deletes
from tpusim.io.trace import NodeRow, PodRow
from tpusim.policies import make_policy
from tpusim.sim.driver import (
    Simulator,
    SimulatorConfig,
    SweepLane,
    enable_compile_cache,
    format_sweep_table,
    schedule_pods_sweep,
    tiebreak_rank,
)
from tpusim.sim.engine import make_replay
from tpusim.sim.step import resolve_weights
from tpusim.sim.table_engine import build_pod_types, make_table_replay
from tpusim.sim.typical import TypicalPodsConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# resolve_weights + input validation (no compiles)
# ---------------------------------------------------------------------------


def test_resolve_weights():
    policies = [(make_policy("FGDScore"), 1000),
                (make_policy("BestFitScore"), 500)]
    np.testing.assert_array_equal(
        np.asarray(resolve_weights(policies)), [1000, 500]
    )
    np.testing.assert_array_equal(
        np.asarray(resolve_weights(policies, [7, 8])), [7, 8]
    )
    assert resolve_weights(policies, [7, 8]).dtype == jnp.int32
    with pytest.raises(ValueError, match="does not match"):
        resolve_weights(policies, [1, 2, 3])


def _mk_cluster(rng, n=16):
    return [
        NodeRow(f"n{i:03d}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], n))
    ]


def _mk_pods(rng, n=40):
    out = []
    for i in range(n):
        gpu = int(rng.choice([0, 1, 2]))
        milli = 1000 if gpu > 1 else int(rng.choice([0, 300, 500, 1000]))
        if gpu == 0:
            milli = 0
        out.append(
            PodRow(f"p{i:04d}", int(rng.choice([1000, 2000, 4000])), 2048,
                   gpu, milli)
        )
    return out


def _cfg(seed, policies=(("FGDScore", 1000),), gpu_sel="FGDScore", **kw):
    base = dict(
        policies=policies,
        gpu_sel_method=gpu_sel,
        seed=seed,
        report_per_event=False,
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
    )
    base.update(kw)
    return SimulatorConfig(**base)


def test_sweep_input_validation():
    rng = np.random.default_rng(3)
    nodes, pods = _mk_cluster(rng), _mk_pods(rng, 10)

    sim = Simulator(nodes, _cfg(42))
    sim.set_workload_pods(pods)
    with pytest.raises(ValueError, match=r"\[B, 1\] matrix"):
        sim.run_sweep([1000, 500])  # 1-D, not [B, P]
    with pytest.raises(ValueError, match=r"\[B, 1\] matrix"):
        sim.run_sweep([[1000, 500]])  # wrong policy count
    with pytest.raises(ValueError, match="at least one config"):
        sim.run_sweep(np.zeros((0, 1), np.int32))
    with pytest.raises(ValueError, match="seeds has 3"):
        sim.run_sweep([[1000], [900]], seeds=[1, 2, 3])

    sim = Simulator(nodes, _cfg(42, record_decisions=True))
    sim.set_workload_pods(pods)
    with pytest.raises(ValueError, match="decisions"):
        sim.run_sweep([[1000]])

    sim = Simulator(nodes, _cfg(42, series_every=4))
    sim.set_workload_pods(pods)
    with pytest.raises(ValueError, match="series"):
        sim.run_sweep([[1000]])


def test_digest_weight_vocabulary(tmp_path):
    """Weights are a RUN input (digest moves with them — checkpoint
    resume across a weight change is impossible) but NOT a table-build
    input (one cached table set serves every weight vector)."""
    from tpusim.io.trace import build_events, pods_to_specs

    rng = np.random.default_rng(4)
    nodes, pods = _mk_cluster(rng), _mk_pods(rng, 12)

    def digests(weights):
        sim = Simulator(
            nodes, _cfg(42, policies=(("FGDScore", weights),))
        )
        sim.set_workload_pods(pods)
        sim.set_typical_pods()
        trace = sim.prepare_pods()
        specs = pods_to_specs(trace, sim.node_index)
        ev_kind, ev_pod = build_events(trace)
        types = build_pod_types(specs)
        run = sim._run_digest(
            sim.init_state, specs, np.asarray(ev_kind), np.asarray(ev_pod),
            np.asarray(jax.random.PRNGKey(42)), np.asarray(sim.rank),
        )
        tbl = sim._tables_digest(sim.init_state, types)
        return run, tbl

    run_a, tbl_a = digests(1000)
    run_a2, tbl_a2 = digests(1000)
    run_b, tbl_b = digests(999)
    assert run_a == run_a2 and tbl_a == tbl_a2  # deterministic
    assert run_a != run_b  # weights joined the run-input vocabulary
    assert tbl_a == tbl_b  # ...but never the (weight-independent) build


def test_format_sweep_table():
    lane = SweepLane(
        weights=np.asarray([1000, 500], np.int32), seed=42,
        placed_node=np.asarray([0, 1, -1]), dev_mask=np.zeros((3, 8), bool),
        ever_failed=np.asarray([False, False, True]), counters=None,
        metrics=None, state=None, events=5, placed=2, failed=1,
        gpu_alloc_pct=12.5, frag_gpu_milli=321.0,
    )
    text = format_sweep_table([lane], [("FGDScore", 1000),
                                       ("BestFitScore", 500)])
    assert "weights(FGDScore,BestFitScore)" in text
    assert "1000,500" in text and "12.50" in text and "321" in text


def test_enable_compile_cache(tmp_path, monkeypatch):
    """Resolution order: explicit dir > $TPUSIM_COMPILE_CACHE_DIR >
    disabled; the chosen dir is created and wired into jax.config."""
    prev = jax.config.jax_compilation_cache_dir
    try:
        monkeypatch.delenv("TPUSIM_COMPILE_CACHE_DIR", raising=False)
        assert enable_compile_cache("") is None

        d1 = str(tmp_path / "explicit")
        assert enable_compile_cache(d1) == d1
        assert os.path.isdir(d1)
        assert jax.config.jax_compilation_cache_dir == d1

        d2 = str(tmp_path / "from_env")
        monkeypatch.setenv("TPUSIM_COMPILE_CACHE_DIR", d2)
        assert enable_compile_cache("") == d2
        assert enable_compile_cache(d1) == d1  # explicit wins over env

        # the cache actually takes: jax latches cache-used once per
        # process at the FIRST compile (which import-time jits always
        # win), so enable_compile_cache must clear the latch — a fresh
        # compile after wiring must land an entry on disk
        jax.jit(lambda x: x * 3 + 1)(jnp.arange(7))
        assert os.listdir(d1), "no persistent-cache entry written"
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_note_compile_cache_heuristic():
    """The obs run record notes the probable persistent-cache outcome via
    the dispatch-wall heuristic: enabled + sub-threshold first scan
    dispatch = probable hit."""
    from tpusim.obs import Recorder, note_compile_cache

    rec = Recorder()
    with rec.span("scan") as h:
        h.dispatched()
    rec.spans[0].dispatch_s = 0.12
    info = note_compile_cache(rec, enabled=True, cache_dir="/tmp/cc")
    assert info["probable_hit"] is True
    record = rec.snapshot().to_record()
    assert record["timing"]["compile_cache"]["dir"] == "/tmp/cc"

    rec = Recorder()
    with rec.span("scan") as h:
        h.dispatched()
    rec.spans[0].dispatch_s = 6.5
    assert note_compile_cache(rec, enabled=True)["probable_hit"] is False
    # cache off + fast dispatch is still not a hit
    assert note_compile_cache(rec, enabled=False)["probable_hit"] is False
    # never assessed -> no block in the record
    rec2 = Recorder()
    assert "compile_cache" not in rec2.snapshot().to_record()["timing"]


# ---------------------------------------------------------------------------
# sweep lanes == standalone baked-weight runs (tier-1: one table family)
# ---------------------------------------------------------------------------


def _assert_lane_matches(lane, res, telemetry=None):
    from tpusim.obs.counters import INVARIANT_FIELDS, COUNTER_FIELDS

    np.testing.assert_array_equal(lane.placed_node, np.asarray(res.placed_node))
    np.testing.assert_array_equal(lane.dev_mask, np.asarray(res.dev_mask))
    assert lane.failed == len(res.unscheduled_pods)
    for a, b in zip(jax.tree.leaves(lane.state), jax.tree.leaves(res.state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    if telemetry is not None and telemetry.counters is not None:
        # engine-invariant counter vocabulary, both sides pad-corrected
        got = dict(zip(COUNTER_FIELDS, (int(c) for c in lane.counters)))
        assert all(
            got[f] == telemetry.counters[f] for f in INVARIANT_FIELDS
        ), (got, telemetry.counters)


@pytest.mark.slow  # three standalone oracles + the vmapped sweep
# (~10 s of compiles) — ISSUE 19 tier-1 buy-back, resume-smoke runs it
def test_sweep_matches_standalone_table():
    """Each lane of a table-engine config-axis sweep must equal the
    standalone run with that weight row baked into the config — same
    placements, device masks, final state, counters — including a
    zero-weight row and duplicated rows."""
    rng = np.random.default_rng(5)
    nodes, pods = _mk_cluster(rng), _mk_pods(rng)
    base = (("FGDScore", 1000), ("BestFitScore", 500))
    grid = [[1000, 500], [100, 2000], [0, 1000], [1000, 500]]

    # one standalone oracle per DISTINCT row (row 3 duplicates row 0 —
    # its lane is pinned against lane 0 below, so a fourth standalone
    # run would add wall without coverage; tier-1 trim, ISSUE 11)
    singles = []
    for w in grid[:3]:
        pol = (("FGDScore", w[0]), ("BestFitScore", w[1]))
        sim = Simulator(nodes, _cfg(42, pol))
        sim.set_workload_pods(pods)
        res = sim.run()
        singles.append((res, res.telemetry))
    singles.append(singles[0])

    # heartbeat_every set: the sweep must strip the in-scan heartbeat
    # (its cond has no batched form) and replay on the heartbeat-free
    # build of the same family — trajectories unchanged
    sim = Simulator(nodes, _cfg(42, base, heartbeat_every=10_000))
    sim.set_workload_pods(pods)
    lanes = sim.run_sweep(grid)
    assert len(lanes) == len(grid)
    assert "vmap sweep" in sim._last_engine
    for lane, (res, tel) in zip(lanes, singles):
        _assert_lane_matches(lane, res, tel)
    # duplicated rows give bit-identical lanes
    np.testing.assert_array_equal(lanes[0].placed_node, lanes[3].placed_node)

    # one jaxpr per family: replayers differing only in weights share one
    # underlying engine (the machinery the standalone runs above used)
    engines = {
        id(make_table_replay(
            [(make_policy("FGDScore"), wrow[0]),
             (make_policy("BestFitScore"), wrow[1])],
            gpu_sel="FGDScore",
        ).engine)
        for wrow in grid
    }
    assert len(engines) == 1


@pytest.mark.slow  # a full CLI sweep replay (~4 s) — ISSUE 19 tier-1
# buy-back, resume-smoke runs it
def test_apply_sweep_weights_cli(tmp_path):
    """`tpusim apply --sweep-weights weights.json` — the CLI face: loads
    a {"weights": ..., "seeds": ...} grid, replays it as one sweep, and
    prints the per-config summary table."""
    import json

    from tpusim.apply import Applier, ApplyOptions

    wfile = tmp_path / "weights.json"
    wfile.write_text(json.dumps(
        {"weights": [[1000], [500], [1]], "seeds": [42, 42, 42]}
    ))
    out = io.StringIO()
    applier = Applier(ApplyOptions(
        simon_config=os.path.join(REPO, "example/test-cluster-config.yaml"),
        default_scheduler_config=os.path.join(
            REPO, "example/test-scheduler-config.yaml"
        ),
        base_dir=REPO,
        sweep_weights=str(wfile),
    ))
    result = applier.run(out=out)
    text = out.getvalue()
    assert result is None  # sweep mode returns no single-run result
    assert "[Sweep] 3 configs" in text
    assert "weights(FGDScore)" in text
    # one row per config with its weight vector
    for w in ("1000", "500", "1"):
        assert any(
            line.split()[1] == w for line in text.splitlines()
            if line.strip() and line.split()[0].isdigit()
        ), (w, text)

    # the CLI main threads the flag through to ApplyOptions (regression:
    # a declared-but-unthreaded argparse flag would silently no-op into
    # a full standalone run)
    from tpusim.cli import main

    rc = main([
        "apply", "-f", os.path.join(REPO, "example/test-cluster-config.yaml"),
        "-s", os.path.join(REPO, "example/test-scheduler-config.yaml"),
        "--base-dir", REPO,
        "--sweep-weights", str(wfile),
    ])
    assert rc == 0

    # a bare list-of-rows payload parses too, and an empty one is loud
    bare = tmp_path / "bare.json"
    bare.write_text("[]")
    applier = Applier(ApplyOptions(
        simon_config=os.path.join(REPO, "example/test-cluster-config.yaml"),
        default_scheduler_config=os.path.join(
            REPO, "example/test-scheduler-config.yaml"
        ),
        base_dir=REPO,
        sweep_weights=str(bare),
    ))
    with pytest.raises(ValueError, match="no weight rows"):
        applier.run(out=io.StringIO())


# ---------------------------------------------------------------------------
# cross-engine bit-identity under a non-static weight operand (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize(
    "mix,gpu_sel",
    [
        ([("FGDScore", 1000), ("BestFitScore", 500)], "FGDScore"),
        ([("PWRScore", 800), ("DotProductScore", 300)], "PWRScore"),
        ([("RandomScore", 1000)], "random"),
    ],
    ids=["fgd+bestfit", "pwr+dotprod", "random"],
)
def test_weight_operand_cross_engine(mix, gpu_sel):
    """sequential == flat table == blocked table (== shard_map where the
    config allows) for EVERY weight vector of a grid passed as a traced
    operand. The blocked lane is the weight-operand blocked-summary
    drift check: bt/br/bn are built in-scan from the operand, and the
    minmax/pwr stored-extrema rebuild path must stay exact under it."""
    from tpusim.parallel import make_mesh, pad_nodes, shard_state
    from tpusim.parallel.shard_engine import make_shardmap_table_replay

    rng = np.random.default_rng(11)
    state, tp = random_cluster(rng, num_nodes=21)
    pods = random_pods(rng, num_pods=48)
    ev_kind, ev_pod = _events_with_deletes(48, rng)
    types = build_pod_types(pods)
    policies = [(make_policy(n), w) for n, w in mix]
    key = jax.random.PRNGKey(7)
    rank = jnp.asarray(tiebreak_rank(21, seed=3))

    seq = make_replay(policies, gpu_sel=gpu_sel, report=False)
    flat = make_table_replay(policies, gpu_sel=gpu_sel)
    blocked = make_table_replay(policies, gpu_sel=gpu_sel, block_size=8)
    shard = None
    if gpu_sel != "random" and len(jax.devices()) >= 8:
        mesh = make_mesh(8)
        pstate, prank = pad_nodes(state, rank, 8)
        pstate = shard_state(pstate, mesh)
        shard = make_shardmap_table_replay(policies, mesh, gpu_sel=gpu_sel)

    grid = [[w for _, w in mix],  # the static row: operand == baked
            [1 for _ in mix],
            [3777 * (i + 1) for i in range(len(mix))]]
    for w in grid:
        r_seq = seq(state, pods, ev_kind, ev_pod, tp, key, rank, weights=w)
        r_flat = flat(
            state, pods, types, ev_kind, ev_pod, tp, key, rank, weights=w
        )
        r_blk = blocked(
            state, pods, types, ev_kind, ev_pod, tp, key, rank, weights=w
        )
        for r in (r_flat, r_blk):
            np.testing.assert_array_equal(
                np.asarray(r_seq.placed_node), np.asarray(r.placed_node)
            )
            np.testing.assert_array_equal(
                np.asarray(r_seq.dev_mask), np.asarray(r.dev_mask)
            )
            for a, b in zip(jax.tree.leaves(r_seq.state),
                            jax.tree.leaves(r.state)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        if shard is not None:
            r_sh = shard(
                pstate, pods, types, ev_kind, ev_pod, tp, key, prank,
                weights=w,
            )
            np.testing.assert_array_equal(
                np.asarray(r_seq.placed_node), np.asarray(r_sh.placed_node)
            )
            np.testing.assert_array_equal(
                np.asarray(r_seq.dev_mask), np.asarray(r_sh.dev_mask)
            )
            n = state.num_nodes
            for a, b in zip(jax.tree.leaves(r_seq.state),
                            jax.tree.leaves(r_sh.state)):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b)[:n]
                )


@pytest.mark.slow
def test_sweep_sequential_and_seeds():
    """The forced-sequential sweep path, plus per-lane SEEDS: a lane's
    seed drives its PRNG key and tie-break rank exactly like cfg.seed
    does standalone (shuffle off so all lanes share one workload)."""
    rng = np.random.default_rng(6)
    nodes, pods = _mk_cluster(rng), _mk_pods(rng, 24)
    grid = [[1000], [250]]
    seeds = [41, 43]

    singles = []
    for w, s in zip(grid, seeds):
        sim = Simulator(nodes, _cfg(
            s, policies=(("RandomScore", w[0]),), gpu_sel="random",
            engine="sequential", shuffle_pod=False,
        ))
        sim.set_workload_pods(pods)
        singles.append(sim.run())

    sim = Simulator(nodes, _cfg(
        42, policies=(("RandomScore", 1000),), gpu_sel="random",
        engine="sequential", shuffle_pod=False,
    ))
    sim.set_workload_pods(pods)
    lanes = sim.run_sweep(grid, seeds=seeds)
    assert "sequential" in sim._last_engine
    for lane, res in zip(lanes, singles):
        _assert_lane_matches(lane, res)


# ---------------------------------------------------------------------------
# openb acceptance: one compile, lane identity, bounded marginal (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_openb_sweep_acceptance():
    """ISSUE 6 acceptance: a B=16 weight sweep over the openb prefix —
    ONE scan span (asserted via obs spans), a different weight grid
    reuses the compiled executable (zero recompiles), sampled lanes
    bit-identical to standalone baked-weight runs, and the marginal
    per-config cost bounded: ≤ 1/5 of a standalone warm replay on
    accelerator backends; on CPU (where vmap can only strip the per-op
    dispatch overhead — ENGINES.md Round 11) it must still beat the
    standalone warm replay outright."""
    from tpusim.io.trace import (
        build_events,
        load_node_csv,
        load_pod_csv,
        pods_to_specs,
    )
    from tpusim.sim.driver import _sweep_engine

    nodes = load_node_csv(
        os.path.join(REPO, "data/csv/openb_node_list_gpu_node.csv")
    )
    pods = load_pod_csv(
        os.path.join(REPO, "data/csv/openb_pod_list_default.csv")
    )[:400]
    b = 16
    # a 2-policy mix: relative weights genuinely reshape placements (a
    # single positive weight only scales the argmax)
    base = (("FGDScore", 1000), ("BestFitScore", 500))
    grid = np.stack(
        [np.asarray([1000 - 37 * i, 100 + 60 * i], np.int32)
         for i in range(b)]
    )

    sim = Simulator(nodes, _cfg(42, base))
    sim.set_workload_pods(pods)
    lanes = sim.run_sweep(grid)
    assert len(lanes) == b

    # exactly one scan dispatch for all 16 configs
    scans = [s for s in sim.obs.spans if s.name == "scan"]
    assert len(scans) == 1, [s.name for s in sim.obs.spans]

    # a different weight grid must NOT add a compiled executable
    fn = _sweep_engine(sim._table_fn.engine.replay, table=True)
    before = fn._cache_size()
    grid2 = np.stack(
        [np.asarray([500 + 11 * i, 900 - 23 * i], np.int32)
         for i in range(b)]
    )
    sim.run_sweep(grid2)
    assert fn._cache_size() == before

    # sampled lanes are bit-identical to standalone baked-weight runs
    for i in (0, 7, 15):
        single = Simulator(nodes, _cfg(42, policies=(
            ("FGDScore", int(grid[i, 0])),
            ("BestFitScore", int(grid[i, 1])),
        )))
        single.set_workload_pods(pods)
        res = single.run()
        _assert_lane_matches(lanes[i], res, res.telemetry)

    # distinct weight rows genuinely diverge somewhere across the grid
    assert any(
        not np.array_equal(lanes[0].placed_node, ln.placed_node)
        for ln in lanes[1:]
    )

    # marginal per-config cost: warm B=16 vs warm B=1 slope against a
    # standalone warm replay
    trace = sim.prepare_pods()
    specs = pods_to_specs(trace)
    ev_kind, ev_pod = build_events(trace)
    ev_kind, ev_pod = jnp.asarray(ev_kind), jnp.asarray(ev_pod)
    key = jax.random.PRNGKey(42)

    def standalone():
        # bucket matches the sweep's default so both sides replay the
        # same padded event count
        res = sim.run_events(
            sim.init_state, specs, ev_kind, ev_pod, key, bucket=512
        )
        jax.block_until_ready(res.state)

    def warm(fn_, reps=3):
        fn_()
        walls = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn_()
            walls.append(time.perf_counter() - t0)
        return min(walls)

    sw = warm(standalone)
    w16 = warm(lambda: schedule_pods_sweep(sim, trace, grid))
    w1 = warm(lambda: schedule_pods_sweep(sim, trace, grid[:1]))
    marginal = max(w16 - w1, 0.0) / (b - 1)
    bound = 0.2 if jax.default_backend() != "cpu" else 1.0
    assert marginal <= bound * sw, (marginal, sw, jax.default_backend())
    # and the whole 16-config batch beats 16 standalone warm replays
    assert w16 < b * sw, (w16, sw)


@pytest.mark.slow  # tier-1 trim, ISSUE 16: rides resume-smoke
def test_sweep_multi_stream_donation(monkeypatch):
    """ISSUE 15 satellite: the multi-trace sweep's per-lane event-stream
    buffer is DONATED when nothing reads it after dispatch (the
    sweep/service lane runs report_per_event=False), finishing the PR 11
    donation story for the batched surfaces. Pins: (1) the donating
    wrapper is the one the dispatch resolves for report-off configs and
    carries the ev_pod argnum; (2) two waves of different tuned traces
    produce bit-identical lanes to fresh standalone runs AND add zero
    executables (the zero-recompile bookkeeping is donation-invariant —
    the (engine, donate, donate_streams) cache key keeps one wrapper per
    family); (3) a report-ON config keeps the non-donating wrapper (the
    metrics postpass re-reads the streams)."""
    from tpusim.sim.driver import _sweep_engine_multi

    rng = np.random.default_rng(29)
    nodes, pods = _mk_cluster(rng), _mk_pods(rng, 24)
    # engine="table" pins the table-form wrapper (the service lane's
    # path) regardless of the events-per-type heuristic
    sim = Simulator(nodes, _cfg(42, engine="table"))
    sim.set_workload_pods(pods)
    grid = np.asarray([[1000], [1000]], np.int32)

    fn_don = _sweep_engine_multi(
        sim._table_fn.engine.replay, table=True, donate_streams=True
    )
    fn_plain = _sweep_engine_multi(
        sim._table_fn.engine.replay, table=True, donate_streams=False
    )
    assert fn_don is not fn_plain  # distinct wrappers, one cache each
    # counts are read RELATIVE to this point — the wrappers are
    # process-global, so sibling tests may have compiled other shapes
    # into either one (the test_svc.py discipline)
    don0 = fn_don._cache_size()
    plain0 = fn_plain._cache_size()

    lanes1 = sim.run_sweep(grid, tunes=[0.0, 0.3])
    before = fn_don._cache_size()
    assert before == don0 + 1  # report-off dispatch resolved the donor
    assert fn_plain._cache_size() == plain0  # ...never the other
    lanes2 = sim.run_sweep(grid, tunes=[0.0, 0.3])
    assert fn_don._cache_size() == before  # second wave: zero recompiles
    for l1, l2 in zip(lanes1, lanes2):
        assert np.array_equal(l1.placed_node, l2.placed_node)

    # lane 0 (tune 0.0) == the plain standalone run
    single = Simulator(nodes, _cfg(42, engine="table"))
    single.set_workload_pods(pods)
    res = single.run()
    assert np.array_equal(
        lanes1[0].placed_node, res.placed_node[:len(lanes1[0].placed_node)]
    )

    # report-on config: the metrics postpass reads the streams after
    # dispatch, so the dispatch must resolve the NON-donating twin
    sim_r = Simulator(nodes, _cfg(42, engine="table", report_per_event=True))
    sim_r.set_workload_pods(pods)
    plain_before = fn_plain._cache_size()
    don_before = fn_don._cache_size()
    sim_r.run_sweep(grid, tunes=[0.0, 0.3])
    assert fn_plain._cache_size() == plain_before + 1
    assert fn_don._cache_size() == don_before
