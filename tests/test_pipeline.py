"""The shard engine's software-pipelined commit (ISSUE 11).

Acceptance pins:
- pipelined == unpipelined bit-identity (placements, telemetry,
  counters, final state) across policies/mixes/gpu_sel and mesh shapes,
  and under the fault lane (retry pops + DOWN-row resets through the
  pending registers);
- run_chunk kill/resume splits: a cut always lands between an event and
  its deferred Bind (the commit applies at the top of the NEXT
  iteration), so every boundary must resume bit-identically — including
  through host numpy round-trips and under fault-lane retry pops;
- buffer donation (run_chunk_donated): bit-identical to the
  non-donating entry, actually consumes the input carry, and the
  kill/resume contract holds with donation armed for the table AND
  shard engines.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import random_cluster, random_pods
from tpusim.io.trace import tiebreak_rank
from tpusim.policies import make_policy
from tpusim.sim.table_engine import build_pod_types, make_table_replay

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs >= 2 virtual devices"
)


@functools.lru_cache(maxsize=None)
def _shard_engine(pol_names, gpu_sel, n_dev, block, faults, pipelined):
    """One shard replayer per config for the whole module — the builder
    has no cache of its own, and every build is a fresh ~2 s compile."""
    from tpusim.parallel import make_mesh
    from tpusim.parallel.shard_engine import make_shardmap_table_replay

    policies = [(make_policy(n), w) for n, w in pol_names]
    return make_shardmap_table_replay(
        policies, make_mesh(n_dev), gpu_sel=gpu_sel, block_size=block,
        faults=faults, pipelined=pipelined,
    )


def _fixture(n_dev, num_nodes=22, num_pods=44, seed=9):
    from tests.test_table_engine import _events_with_deletes
    from tpusim.parallel import pad_nodes, shard_state

    rng = np.random.default_rng(seed)
    state, tp = random_cluster(rng, num_nodes=num_nodes)
    pods = random_pods(rng, num_pods=num_pods)
    ev_kind, ev_pod = _events_with_deletes(num_pods, rng)
    types = build_pod_types(pods)
    rank = jnp.asarray(tiebreak_rank(num_nodes, seed=3))
    from tpusim.parallel import make_mesh

    mesh = make_mesh(n_dev)
    pstate, prank = pad_nodes(state, rank, n_dev)
    pstate = shard_state(pstate, mesh)
    key = jax.random.PRNGKey(7)
    return state, tp, pods, types, ev_kind, ev_pod, pstate, prank, key


def _assert_replays_equal(r0, r1):
    assert np.array_equal(np.asarray(r0.placed_node),
                          np.asarray(r1.placed_node))
    assert np.array_equal(np.asarray(r0.dev_mask), np.asarray(r1.dev_mask))
    assert np.array_equal(np.asarray(r0.ever_failed),
                          np.asarray(r1.ever_failed))
    assert np.array_equal(np.asarray(r0.event_node),
                          np.asarray(r1.event_node))
    assert np.array_equal(np.asarray(r0.event_dev),
                          np.asarray(r1.event_dev))
    assert np.array_equal(np.asarray(r0.counters), np.asarray(r1.counters))
    for f, (a, b) in zip(
        r0.state._fields,
        zip(jax.tree.leaves(r0.state), jax.tree.leaves(r1.state)),
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b)), f


PIPE_CONFIGS = [
    # tier-1 keeps one mix on the small mesh with the blocked local
    # select forced (the layout the 1M lane runs); the wider
    # policy/gpu_sel/mesh grid compiles ~2 engines per case and runs
    # under `make resume-smoke`
    ((("FGDScore", 1000), ("BestFitScore", 500)), "FGDScore", 2, 4),
    pytest.param((("FGDScore", 1000),), "FGDScore", 8, 4,
                 marks=pytest.mark.slow),
    pytest.param((("PWRScore", 1000),), "PWRScore", 2, 0,
                 marks=pytest.mark.slow),  # normalized -> flat local path
    pytest.param((("BestFitScore", 1000),), "worst", 8, 0,
                 marks=pytest.mark.slow),
    pytest.param((("GpuPackingScore", 600), ("DotProductScore", 400)),
                 "DotProductScore", 2, 0, marks=pytest.mark.slow),
]


@pytest.mark.parametrize("pol,gpu_sel,n_dev,block", PIPE_CONFIGS,
                         ids=lambda p: str(p))
def test_pipelined_matches_unpipelined(pol, gpu_sel, n_dev, block):
    """The pipelined commit is bit-identical to the unpipelined body —
    placements, device masks, telemetry, counters, final state — for
    policy mixes, normalized policies, and both mesh shapes."""
    (state, tp, pods, types, ev_kind, ev_pod, pstate, prank,
     key) = _fixture(n_dev)
    r_pipe = _shard_engine(pol, gpu_sel, n_dev, block, False, True)(
        pstate, pods, types, ev_kind, ev_pod, tp, key, prank
    )
    r_base = _shard_engine(pol, gpu_sel, n_dev, block, False, False)(
        pstate, pods, types, ev_kind, ev_pod, tp, key, prank
    )
    _assert_replays_equal(r_pipe, r_base)
    # ... and both match the single-device table engine (the standing
    # shard-equality contract)
    policies = [(make_policy(n), w) for n, w in pol]
    r_tab = make_table_replay(policies, gpu_sel=gpu_sel)(
        state, pods, types, ev_kind, ev_pod, tp, key,
        jnp.asarray(tiebreak_rank(state.num_nodes, seed=3)),
    )
    assert np.array_equal(
        np.asarray(r_tab.placed_node), np.asarray(r_pipe.placed_node)
    )
    assert np.array_equal(
        np.asarray(r_tab.dev_mask), np.asarray(r_pipe.dev_mask)
    )


def _fault_inputs(n_dev, seed=11):
    """A merged fault stream (fails + recovers + evictions + retry
    slots) over the 2-device fixture, plus the padded FaultOps/carry."""
    from tpusim.io.trace import NodeRow, PodRow, build_events, pods_to_specs
    from tpusim.sim import fault_lane
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.faults import FaultConfig, generate_fault_schedule

    nodes = [NodeRow(f"host-{i}", 16000, 65536, 2, "V100M16")
             for i in range(3)]
    pods = [PodRow(f"p{i}", 2000, 1024, 1, 500) for i in range(8)]
    sim = Simulator(nodes, SimulatorConfig(
        policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
        report_per_event=False, mesh=n_dev,
    ))
    sim.set_workload_pods(pods)
    sim.set_typical_pods()
    specs = pods_to_specs(pods, sim.node_index)
    ev_kind, ev_pod = build_events(pods, False)
    fcfg = FaultConfig(
        mtbf_events=3, mttr_events=4, evict_every_events=5, seed=seed,
        backoff_base=2, backoff_cap=8, max_retries=2,
    )
    faults = generate_fault_schedule(len(nodes), len(ev_kind), fcfg)
    plan = fault_lane.compile_fault_plan(
        ev_kind, ev_pod, faults, fcfg, len(nodes), len(pods)
    )
    from tpusim.parallel import pad_nodes, shard_state

    n0 = sim.init_state.num_nodes
    state_p, rank_p = pad_nodes(sim.init_state, sim.rank, n_dev)
    n_pad = state_p.num_nodes
    state_p = shard_state(state_p, sim._mesh)
    ops = fault_lane.FaultOps(
        pos=jnp.asarray(plan.pos), arg=jnp.asarray(plan.arg),
        aux=jnp.asarray(plan.aux), draws=jnp.asarray(plan.draws),
        params=jnp.asarray(plan.params),
        gcnt=jnp.pad(jnp.asarray(sim.init_state.gpu_cnt),
                     (0, n_pad - n0)),
    )
    fc0 = fault_lane.init_fault_carry(len(pods), n_pad, plan.capacity)
    types = build_pod_types(specs)
    key = jax.random.PRNGKey(42)
    return sim, specs, types, plan, ops, fc0, state_p, rank_p, key


@pytest.mark.parametrize("n_dev", [
    2, pytest.param(8, marks=pytest.mark.slow)
])
def test_pipelined_fault_lane_matches_unpipelined(n_dev):
    """Fault kinds flow through the pending registers: retry pops,
    DOWN-row resets, and eviction returns replay bit-identically to the
    unpipelined in-body fault application — per-event fault telemetry
    and the final retry-queue carry included."""
    (sim, specs, types, plan, ops, fc0, state_p, rank_p,
     key) = _fault_inputs(n_dev)
    kind_d, idx_d = jnp.asarray(plan.kind), jnp.asarray(plan.idx)
    pol = (("FGDScore", 1000),)
    outs = []
    for pipelined in (True, False):
        fn = _shard_engine(pol, "FGDScore", n_dev, 0, True, pipelined)
        outs.append(fn(
            state_p, specs, types, kind_d, idx_d, sim.typical, key,
            rank_p, fault_ops=ops, fault_carry0=fc0,
        ))
    a, b = outs
    _assert_replays_equal(a, b)
    for f, (x, y) in zip(
        a.fault_ys._fields,
        zip(jax.tree.leaves(a.fault_ys), jax.tree.leaves(b.fault_ys)),
    ):
        assert np.array_equal(np.asarray(x), np.asarray(y)), f
    for f, (x, y) in zip(
        a.fault_carry._fields,
        zip(jax.tree.leaves(a.fault_carry),
            jax.tree.leaves(b.fault_carry)),
    ):
        assert np.array_equal(np.asarray(x), np.asarray(y)), f


def test_shard_chunk_resume_between_event_and_bind():
    """ISSUE 11 satellite: with the pipelined register, EVERY chunk cut
    lands between an event and its deferred Bind — the commit is still
    in the carry, not in the buffers. Cutting at several boundaries
    (with a host numpy round-trip, the checkpoint surface) must resume
    bit-identically to the one-shot replay."""
    n_dev = 2
    pol = (("FGDScore", 1000), ("BestFitScore", 500))
    (state, tp, pods, types, ev_kind, ev_pod, pstate, prank,
     key) = _fixture(n_dev)
    fn = _shard_engine(pol, "FGDScore", n_dev, 4, False, True)
    ref = fn(pstate, pods, types, ev_kind, ev_pod, tp, key, prank)
    e = int(ev_kind.shape[0])
    for cut in (1, e // 2):
        carry = fn.init_carry(pstate, pods, types, tp, key, prank)
        parts = []
        for a, b in ((0, cut), (cut, e)):
            carry, ys = fn.run_chunk(
                carry, pods, types, ev_kind[a:b], ev_pod[a:b], tp, prank
            )
            # host round-trip: what checkpoint serialization does; jit
            # re-shards the gathered leaves on the way back in
            carry = jax.tree.map(
                lambda x: jnp.asarray(np.asarray(x)), carry
            )
            parts.append(np.asarray(ys[0]))
        st, placed, masks, failed = fn.finish(carry)
        assert np.array_equal(np.asarray(placed),
                              np.asarray(ref.placed_node))
        assert np.array_equal(np.asarray(masks), np.asarray(ref.dev_mask))
        assert np.array_equal(np.asarray(failed),
                              np.asarray(ref.ever_failed))
        assert np.array_equal(np.concatenate(parts),
                              np.asarray(ref.event_node))
        for a_, b_ in zip(jax.tree.leaves(st), jax.tree.leaves(ref.state)):
            assert np.array_equal(np.asarray(a_), np.asarray(b_))


def test_shard_chunk_resume_under_fault_retry_pops():
    """The same cut contract on the fault lane: a boundary inside the
    retry region (after pops have drained part of the queue, with a
    pending fault register in flight) resumes bit-identically —
    FaultCarry, pending registers, and bookkeeping all ride the
    checkpointed carry."""
    n_dev = 2
    (sim, specs, types, plan, ops, fc0, state_p, rank_p,
     key) = _fault_inputs(n_dev)
    pol = (("FGDScore", 1000),)
    fn = _shard_engine(pol, "FGDScore", n_dev, 0, True, True)
    kind_d, idx_d = jnp.asarray(plan.kind), jnp.asarray(plan.idx)
    ref = fn(state_p, specs, types, kind_d, idx_d, sim.typical, key,
             rank_p, fault_ops=ops, fault_carry0=fc0)
    e_m = int(plan.kind.shape[0])
    # cut right after the first retry slot (a popped-and-committed or
    # popped-and-pending retry straddles the boundary), plus mid-stream
    slots = np.flatnonzero(plan.kind == 6)  # EV_RETRY
    cuts = {int(slots[0]) + 1 if slots.size else 1, e_m // 2}
    for cut in sorted(cuts):
        carry = fn.init_carry(state_p, specs, types, sim.typical, key,
                              rank_p, fault_carry0=fc0)
        for a, b in ((0, cut), (cut, e_m)):
            ops_sl = ops._replace(
                pos=ops.pos[a:b], arg=ops.arg[a:b], aux=ops.aux[a:b]
            )
            carry, ys = fn.run_chunk(
                carry, specs, types, kind_d[a:b], idx_d[a:b],
                sim.typical, rank_p, fault_ops=ops_sl,
            )
            carry = jax.tree.map(
                lambda x: jnp.asarray(np.asarray(x)), carry
            )
        st, placed, masks, failed = fn.finish(carry)
        assert np.array_equal(np.asarray(placed),
                              np.asarray(ref.placed_node))
        assert np.array_equal(np.asarray(failed),
                              np.asarray(ref.ever_failed))
        for f, (x, y) in zip(
            ref.fault_carry._fields,
            zip(jax.tree.leaves(ref.fault_carry),
                jax.tree.leaves(carry[1])),
        ):
            xa, ya = np.asarray(x), np.asarray(y)
            # the one-shot result's fault carry is trimmed; compare on
            # the common prefix of each leaf
            assert np.array_equal(
                xa, ya[tuple(slice(0, s) for s in xa.shape)]
            ), f


@pytest.mark.parametrize("engine", ["table", "shard"])
def test_donated_chunk_entry_bit_identical_and_consuming(engine):
    """run_chunk_donated (ISSUE 11): equals the non-donating entry
    bit-for-bit across a kill/resume split (host round-trip between
    chunks — the acceptance's 'donation armed' resume contract), and
    actually consumes its input carry (the donated buffers are
    deleted)."""
    n_dev = 2
    pol = (("FGDScore", 1000), ("BestFitScore", 500))
    (state, tp, pods, types, ev_kind, ev_pod, pstate, prank,
     key) = _fixture(n_dev)
    if engine == "table":
        policies = [(make_policy(n), w) for n, w in pol]
        fn = make_table_replay(policies, gpu_sel="FGDScore")
        st0, rk = state, jnp.asarray(
            tiebreak_rank(state.num_nodes, seed=3)
        )
    else:
        fn = _shard_engine(pol, "FGDScore", n_dev, 4, False, True)
        st0, rk = pstate, prank
    ref = fn(st0, pods, types, ev_kind, ev_pod, tp, key, rk)
    e = int(ev_kind.shape[0])
    cut = e // 2
    carry = fn.init_carry(st0, pods, types, tp, key, rk)
    for i, (a, b) in enumerate(((0, cut), (cut, e))):
        prev_leaves = jax.tree.leaves(carry)
        # snapshot-then-donate: exactly the driver checkpoint order
        host = jax.tree.map(np.asarray, carry)
        carry, ys = fn.run_chunk_donated(
            carry, pods, types, ev_kind[a:b], ev_pod[a:b], tp, rk
        )
        jax.block_until_ready(jax.tree.leaves(carry))
        # the donated input really was consumed: every sizable buffer
        # (tables, state rows, bookkeeping) must be deleted on the
        # pipelined shard engine (its body is strictly write-then-read,
        # so every buffer is donatable). The table engine's flat path
        # still reads score rows inside its event switch, which can
        # leave one buffer un-aliasable — donation is per-buffer
        # best-effort there, so require only that MOST big leaves were
        # consumed (the state/bookkeeping ones always are).
        big = [l for l in prev_leaves if l.size >= 1024]
        alive = [
            l for l in big
            if not getattr(l, "is_deleted", lambda: True)()
        ]
        if engine == "shard":
            assert not alive, (
                f"{len(alive)} big donated buffers still alive"
            )
        else:
            assert len(alive) <= 1, (
                f"{len(alive)}/{len(big)} big donated buffers still alive"
            )
        if i == 0:
            # kill/resume: rebuild the carry from the host snapshot and
            # re-run the first chunk through the donating entry — the
            # continuation below must still match the one-shot replay
            carry = jax.tree.map(jnp.asarray, host)
            carry, ys = fn.run_chunk_donated(
                carry, pods, types, ev_kind[a:b], ev_pod[a:b], tp, rk
            )
    st, placed, masks, failed = fn.finish(carry)
    assert np.array_equal(np.asarray(placed), np.asarray(ref.placed_node))
    assert np.array_equal(np.asarray(masks), np.asarray(ref.dev_mask))
    for a_, b_ in zip(jax.tree.leaves(st), jax.tree.leaves(ref.state)):
        assert np.array_equal(np.asarray(a_), np.asarray(b_))
