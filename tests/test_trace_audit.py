"""Fleet flight recorder (ISSUE 19): the hash-chained audit log, the
per-process span recorder + cross-process stitcher, trace-id
propagation through the fleet protocol (including an epoch-bump
re-register and an orphan steal), steal-visibility accounting, and the
aggregated /metrics label hygiene.

Tier-1 slice: pure protocol, no device, no spawned worker fleet — the
stitch/chain/fence cases run on handcrafted files and the in-process
FleetService stack (the test_fleet idiom). The process-spawning case
(a real kill -9'd recorder) is slow-marked and runs under
`make resume-smoke`; the full real-HTTP fleet end-to-end lives in
`make fleet-trace-smoke`.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from tpusim.io import storage
from tpusim.io.trace import NodeRow, PodRow
from tpusim.obs import audit as obs_audit
from tpusim.obs import trace as obs_trace
from tpusim.obs.emitters import parse_prometheus_text
from tpusim.svc import jobs as svc_jobs
from tpusim.svc.api import JobService
from tpusim.svc.batcher import JobQueue
from tpusim.svc.fleet import FleetService, worker_metrics_text
from tpusim.svc.worker import TraceRef

FAM = [["FGDScore", 1000], ["BestFitScore", 500]]


@pytest.fixture(scope="module")
def trace():
    rng = np.random.default_rng(3)
    nodes = [
        NodeRow(f"n{i:03d}", 32000, 131072, int(g),
                "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], 16))
    ]
    pods = []
    for i in range(24):
        gpu = int(rng.choice([0, 1, 2]))
        milli = 1000 if gpu > 1 else int(rng.choice([0, 300, 500, 1000]))
        if gpu == 0:
            milli = 0
        pods.append(
            PodRow(f"p{i:04d}", int(rng.choice([1000, 2000, 4000])),
                   2048, gpu, milli)
        )
    return TraceRef(
        "default", nodes, pods, svc_jobs.trace_digest(nodes, pods)
    )


def _fleet_stack(trace, tmp_path, lease_s=0.25):
    queue = JobQueue(maxsize=32, lane_width=2, lease_s=lease_s)
    service = JobService(queue, None, {"default": trace}, str(tmp_path))
    service.bucket = 512
    service.spans = obs_trace.SpanRecorder(str(tmp_path), "coord-test")
    service.audit = obs_audit.AuditLog(str(tmp_path), "coord-test")
    fleet = FleetService(service)
    service.fleet = fleet
    return queue, service, fleet


def _call(fleet, path, doc):
    resp = fleet.handle("POST", path, json.dumps(doc).encode())
    return resp[0], json.loads(resp[2].decode())


# ---------------------------------------------------------------------------
# 1. the hash chain (io.storage) — append, verify, tamper
# ---------------------------------------------------------------------------


def test_chain_append_and_verify(tmp_path):
    path = str(tmp_path / "chain.jsonl")
    for i in range(5):
        storage.chain_append(path, {"kind": "k", "i": i})
    assert storage.chain_verify(path) == 5
    records = storage.chain_records(path)
    assert [r["i"] for r, _ in records] == list(range(5))
    # every record names its predecessor; genesis opens the chain
    assert records[0][0]["prev"] == storage.CHAIN_GENESIS
    for (_, h), (r2, _) in zip(records, records[1:]):
        assert r2["prev"] == h


def test_chain_rejects_truncation(tmp_path):
    path = str(tmp_path / "chain.jsonl")
    for i in range(4):
        storage.chain_append(path, {"i": i})
    with open(path) as f:
        lines = f.read().splitlines()
    with open(path, "w") as f:
        f.write("\n".join(lines[:-1]) + "\n")
    # links still verify line-to-line, but the head sidecar knows the
    # chain is SHORTER than it was — truncation fails loudly
    with pytest.raises(ValueError):
        storage.chain_verify(path)


def test_chain_rejects_edit(tmp_path):
    path = str(tmp_path / "chain.jsonl")
    for i in range(4):
        storage.chain_append(path, {"i": i, "who": "w1"})
    with open(path) as f:
        lines = f.read().splitlines()
    doc = json.loads(lines[1])
    doc["who"] = "w2"  # rewrite history
    lines[1] = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.raises(ValueError):
        storage.chain_records(path)
    with pytest.raises(ValueError):
        storage.chain_verify(path)


# ---------------------------------------------------------------------------
# 2. the audit log + `tpusim audit`
# ---------------------------------------------------------------------------


def test_audit_log_tail_filters_and_cli(tmp_path):
    art = str(tmp_path)
    log = obs_audit.AuditLog(art, "coord-1")
    log.emit("takeover", coordinator="c1", epoch=3)
    log.emit("steal", job="a" * 64, worker="w1", reason="lease_expired")
    log.emit("requeue", job="b" * 64, worker="w1", reason="worker-dead")
    log.emit("steal", job="c" * 64, worker="w2", reason="lease_expired")
    assert obs_audit.verify(art) == 4

    assert [r["kind"] for r in obs_audit.tail(art, n=0)] == [
        "takeover", "steal", "requeue", "steal"]
    assert len(obs_audit.tail(art, n=0, kind="steal")) == 2
    assert [r["job"] for r in obs_audit.tail(art, n=0, worker="w1")] == [
        "a" * 64, "b" * 64]
    # job filters match by prefix (digests are long)
    assert len(obs_audit.tail(art, n=0, job="a" * 8)) == 1
    assert len(obs_audit.tail(art, n=1)) == 1

    from tpusim.cli import main
    assert main(["audit", "-d", art]) == 0
    assert main(["audit", "-d", art, "--verify"]) == 0
    assert main(["audit", "-d", str(tmp_path / "nope")]) == 2
    # truncate: the verify verb exits 1, loudly
    path = obs_audit.audit_path(art)
    with open(path) as f:
        lines = f.read().splitlines()
    with open(path, "w") as f:
        f.write("\n".join(lines[:-1]) + "\n")
    assert main(["audit", "-d", art, "--verify"]) == 1


# ---------------------------------------------------------------------------
# 3. the span recorder + stitcher + `tpusim trace`
# ---------------------------------------------------------------------------


def test_span_recorder_and_stitch(tmp_path):
    art = str(tmp_path)
    job = "d" * 64
    rec = obs_trace.SpanRecorder(art, "coord-9")
    rec.emit(obs_trace.SPAN_ADMIT, 10.0, 10.5, job=job, trace="t1")
    sid = rec.begin(obs_trace.SPAN_DISPATCH, job=job, trace="t1",
                    lane=0)
    rec.end(sid, dispatch_s=1.25)
    with rec.span(obs_trace.SPAN_UPLOAD, job=job, trace="t1") as sp:
        sp.meta["bytes"] = 123
    with pytest.raises(RuntimeError):
        with rec.span(obs_trace.SPAN_VERIFY, job=job, trace="t1"):
            raise RuntimeError("boom")
    rec.emit(obs_trace.SPAN_ADMIT, 11.0, 11.1, job="e" * 64, trace="t2")

    spans, problems = obs_trace.stitch(art, job=job)
    assert problems == []
    assert [s["status"] for s in spans] == ["ok"] * 4
    names = {s["name"] for s in spans}
    assert names == {obs_trace.SPAN_ADMIT, obs_trace.SPAN_DISPATCH,
                     obs_trace.SPAN_UPLOAD, obs_trace.SPAN_VERIFY}
    # begin + end meta fold into one span; the ctx meta and the error
    by_name = {s["name"]: s for s in spans}
    assert by_name[obs_trace.SPAN_DISPATCH]["meta"] == {
        "lane": 0, "dispatch_s": 1.25}
    assert by_name[obs_trace.SPAN_UPLOAD]["meta"] == {"bytes": 123}
    assert by_name[obs_trace.SPAN_VERIFY]["meta"]["error"] == (
        "RuntimeError")
    # trace filter; job prefix filter (the CLI convenience)
    assert len(obs_trace.stitch(art, trace="t2")[0]) == 1
    assert len(obs_trace.stitch(art, job="d" * 12)[0]) == 4

    doc = obs_trace.chrome_trace(spans)
    assert [e for e in doc["traceEvents"] if e["ph"] == "X"]
    text = "\n".join(obs_trace.format_timeline(spans))
    assert obs_trace.SPAN_DISPATCH in text and "coord-9" in text


def test_stitch_abandoned_orphan_and_tamper(tmp_path):
    art = str(tmp_path)
    # a once-real, now-dead pid: a reaped child's
    child = subprocess.Popen([sys.executable, "-c", "pass"])
    child.wait()
    dead_pid = child.pid
    # the killed worker's file: a begin with no end, written by the
    # recorder's own signing path but carrying the dead writer's pid
    doc = {
        "schema": obs_trace.SCHEMA, "ev": "begin", "span": "x-1",
        "name": obs_trace.SPAN_DISPATCH, "job": "f" * 64,
        "trace": "t9", "proc": "worker-dead", "pid": dead_pid,
        "t": 100.0,
    }
    os.makedirs(os.path.join(art, obs_trace.SPANS_DIRNAME))
    dead_file = os.path.join(
        art, obs_trace.SPANS_DIRNAME,
        "worker-dead" + obs_trace.SPANS_SUFFIX,
    )
    with open(dead_file, "w") as f:
        f.write(json.dumps(obs_trace._sign(doc), sort_keys=True,
                           separators=(",", ":")) + "\n")
    # a live recorder ending a span it never began -> orphan
    rec = obs_trace.SpanRecorder(art, "worker-live")
    rec.end("never-began")

    spans, problems = obs_trace.stitch(art)
    assert problems == []
    by_status = {s["status"]: s for s in spans}
    assert by_status["abandoned"]["job"] == "f" * 64
    assert by_status["abandoned"]["proc"] == "worker-dead"
    assert "orphan" in by_status
    text = "\n".join(obs_trace.format_timeline(spans))
    assert "ABANDONED" in text and "ORPHAN" in text

    # an EDITED span line is skipped and reported, never misread
    with open(dead_file) as f:
        line = f.read().splitlines()[0]
    edited = json.loads(line)
    edited["job"] = "0" * 64
    with open(dead_file, "a") as f:
        f.write(json.dumps(edited, sort_keys=True,
                           separators=(",", ":")) + "\n")
    spans2, problems2 = obs_trace.stitch(art)
    assert any("signature mismatch" in p for p in problems2)
    assert not any(s["job"] == "0" * 64 for s in spans2)


def test_trace_cli(tmp_path):
    art = str(tmp_path)
    job = "a" * 64
    rec = obs_trace.SpanRecorder(art, "coord-cli")
    rec.emit(obs_trace.SPAN_ADMIT, 1.0, 1.5, job=job, trace="t1")

    from tpusim.cli import main
    out = str(tmp_path / "trace.json")
    assert main(["trace", job, "-d", art, "--out", out]) == 0
    with open(out) as f:
        assert json.load(f)["traceEvents"]
    assert main(["trace", "ffff", "-d", art]) == 2  # no matching spans
    assert main(["trace", job, "-d", str(tmp_path / "nope")]) == 2


# ---------------------------------------------------------------------------
# 4. trace-id propagation through the fleet protocol (no HTTP, no device)
# ---------------------------------------------------------------------------


def test_trace_header_propagates_to_claim(trace, tmp_path):
    """The id minted at submit rides the X-Tpusim-Trace header into
    admission, tags the coordinator's admit + queue_wait spans, and is
    handed to the claiming worker in the job document."""
    queue, service, fleet = _fleet_stack(trace, tmp_path)
    resp = service.handle(
        "POST", "/jobs",
        json.dumps({"policies": FAM, "weights": [1000, 500],
                    "seed": 42}).encode(),
        {obs_trace.TRACE_HEADER: "cafef00dcafef00d"},
    )
    body = json.loads(resp[2].decode())
    assert resp[0] == 202
    digest = body["digest"]
    assert service.trace_of(digest) == "cafef00dcafef00d"

    _call(fleet, "/workers/register", {"worker": "w1", "pid": 11})
    code, claim = _call(fleet, "/workers/claim", {"worker": "w1"})
    assert code == 200 and claim["jobs"]
    jd = next(j for j in claim["jobs"] if j["digest"] == digest)
    assert jd["trace"] == "cafef00dcafef00d"

    spans, _ = obs_trace.stitch(str(tmp_path), job=digest)
    names = {s["name"] for s in spans}
    assert obs_trace.SPAN_ADMIT in names
    assert obs_trace.SPAN_QUEUE_WAIT in names
    assert {s["trace"] for s in spans} == {"cafef00dcafef00d"}


class _FakeCoord:
    """Just enough of CoordinatorState for the fencing path."""

    def __init__(self, epoch):
        self.epoch = epoch
        self.role = "leader"
        self.noted = []

    def note_epoch(self, e):
        self.noted.append(e)


def test_trace_survives_epoch_bump_and_steal(trace, tmp_path):
    """The failover journey, pure-protocol: a job claimed at epoch N,
    the coordinator bumps to N+1 (a takeover elsewhere), the worker's
    stale-epoch op answers 409 + register, the worker re-registers at
    the new epoch, the abandoned lease expires, and the RE-CLAIMED job
    still carries the trace id minted at submit — with the fence hit,
    the lease expiry and the steal all in the audit chain, and the
    steals-adjusted latency accounting on the job."""
    queue, service, fleet = _fleet_stack(trace, tmp_path, lease_s=0.2)
    coord = _FakeCoord(epoch=5)
    fleet.coord = coord
    art = str(tmp_path)

    resp = service.handle(
        "POST", "/jobs",
        json.dumps({"policies": FAM, "weights": [1234, 500],
                    "seed": 42}).encode(),
        {obs_trace.TRACE_HEADER: "feedbeeffeedbeef"},
    )
    digest = json.loads(resp[2].decode())["digest"]

    _call(fleet, "/workers/register",
          {"worker": "w1", "pid": 11, "epoch": 5})
    code, claim = _call(fleet, "/workers/claim",
                        {"worker": "w1", "epoch": 5})
    assert code == 200
    assert claim["jobs"][0]["trace"] == "feedbeeffeedbeef"
    job = queue.get_by_digest(digest)
    assert job.attempts == 1

    # the takeover happened elsewhere: our epoch is now 6, the
    # worker's next op at 5 is fenced and told to re-register
    coord.epoch = 6
    code, doc = _call(fleet, "/workers/claim",
                      {"worker": "w1", "epoch": 5})
    assert code == 409 and doc["stale_epoch"] and doc["register"]

    # w1's attempt is abandoned (it never completes); a second worker
    # joins at the new epoch and steals the expired lease
    time.sleep(queue.lease_s + 0.1)
    _call(fleet, "/workers/register",
          {"worker": "w2", "pid": 22, "epoch": 6})
    code, claim2 = _call(fleet, "/workers/claim",
                         {"worker": "w2", "epoch": 6})
    assert code == 200
    jd = next(j for j in claim2["jobs"] if j["digest"] == digest)
    assert jd["stolen"] == 1
    assert jd["trace"] == "feedbeeffeedbeef"  # preserved end to end
    assert job.attempts == 2

    svc_jobs.write_result(art, digest, {"placed": 1, "job": digest})
    code, comp = _call(fleet, "/workers/complete",
                       {"worker": "w2", "done": [digest],
                        "dispatch_s": 0.5, "epoch": 6})
    assert code == 200 and comp["acked"] == 1

    # steal-visibility accounting (ISSUE 19): the abandoned attempt's
    # wall is measured, and the adjusted latency subtracts it
    desc = job.describe()
    assert desc["attempts"] == 2
    assert desc["steal_lost_s"] > 0
    # describe() rounds steal_lost_s for display; compare against the
    # job's exact accumulator
    assert desc["adjusted_latency_s"] == pytest.approx(
        max(desc["latency_s"] - job.steal_lost_s, 0.0), abs=1e-6
    )
    lat = queue.latency_percentiles()
    row = next(iter(lat.values()))
    assert row["adjusted_p50_s"] <= row["p50_s"]

    # the whole incident is in the hash chain, in order, intact
    assert obs_audit.verify(art) >= 2
    kinds = [r["kind"] for r in obs_audit.tail(art, n=0)]
    assert "fence_409" in kinds
    assert "steal" in kinds
    steal = obs_audit.tail(art, n=0, kind="steal")[0]
    assert steal["job"] == digest and steal["worker"] == "w1"


# ---------------------------------------------------------------------------
# 5. the aggregated /metrics — label hygiene round-trip
# ---------------------------------------------------------------------------


def test_merged_metrics_escaping_roundtrip(trace, tmp_path):
    """A hostile worker id (quotes, backslashes, a newline) must ride
    escape_label_value into the merged /metrics and round-trip through
    parse_prometheus_text unchanged — the exposition text stays one
    sample per line no matter what the id contains."""
    queue, service, fleet = _fleet_stack(trace, tmp_path)
    evil = 'w"1\\x\ny'
    _call(fleet, "/workers/register", {"worker": evil, "pid": 33})
    service.handle(
        "POST", "/jobs",
        json.dumps({"policies": FAM, "weights": [1000, 500],
                    "seed": 42}).encode(),
        None,
    )
    code, claim = _call(fleet, "/workers/claim", {"worker": evil})
    digest = claim["jobs"][0]["digest"]
    svc_jobs.write_result(str(tmp_path), digest,
                          {"placed": 1, "job": digest})
    push = worker_metrics_text(
        1, 1, 0, 1.5, 1, {"download_bytes": 10, "upload_bytes": 20}
    )
    code, comp = _call(fleet, "/workers/complete",
                       {"worker": evil, "done": [digest],
                        "dispatch_s": 1.5, "probable_hits": 1,
                        "metrics_text": push})
    assert code == 200 and comp["acked"] == 1

    code, ctype, body = fleet.handle("GET", "/metrics", b"")[:3]
    assert code == 200 and ctype.startswith("text/plain")
    text = body.decode()
    series = parse_prometheus_text(text)  # raises on any bad line
    assert series[("tpusim_fleet_workers_live", ())] == 1.0
    assert ("tpusim_fleet_queue_depth", ()) in series
    # the pushed snapshot re-emitted under the worker label, the id
    # restored EXACTLY by the parser's unescape
    key = ("tpusim_worker_batches", (("worker", evil),))
    assert series[key] == 1.0
    assert series[("tpusim_worker_jobs_done", (("worker", evil),))] == 1.0
    assert series[
        ("tpusim_worker_probable_compile_hits", (("worker", evil),))
    ] == 1.0
    # one physical line per sample: the newline in the id was escaped
    assert len([ln for ln in text.splitlines()
                if ln.startswith("tpusim_worker_batches")]) == 1

    # the measured capability profile rides /workers (ISSUE 19)
    row = fleet.registry.describe()[evil]
    prof = row["profile"]
    assert prof["ewma_dispatch_s"] == pytest.approx(1.5)
    assert prof["compile_hit_rate"] == pytest.approx(1.0)
    # a second, faster batch moves the EWMA by 0.7/0.3 smoothing
    svc_jobs.write_result(str(tmp_path), "9" * 64, {"placed": 1})
    _call(fleet, "/workers/complete",
          {"worker": evil, "done": [], "dispatch_s": 0.5})
    prof2 = fleet.registry.describe()[evil]["profile"]
    assert prof2["ewma_dispatch_s"] == pytest.approx(
        0.7 * 1.5 + 0.3 * 0.5)


def test_unparseable_worker_push_never_poisons_metrics(trace, tmp_path):
    queue, service, fleet = _fleet_stack(trace, tmp_path)
    _call(fleet, "/workers/register", {"worker": "w1", "pid": 44})
    code, comp = _call(fleet, "/workers/complete",
                       {"worker": "w1", "done": [],
                        "metrics_text": "this is not exposition {{{"})
    assert code == 200  # the push is dropped, the complete still lands
    code, _, body = fleet.handle("GET", "/metrics", b"")[:3]
    series = parse_prometheus_text(body.decode())
    assert not any(
        dict(labels).get("worker") == "w1" for _, labels in series
    )


# ---------------------------------------------------------------------------
# 6. the real kill -9 (process-spawning: resume-smoke)
# ---------------------------------------------------------------------------


@pytest.mark.slow  # spawns + kill -9s a real recorder process
def test_killed_recorder_stitches_abandoned(tmp_path):
    """A real process begins a dispatch span and is kill -9'd mid-span:
    the stitcher must render the corpse as ABANDONED (end = the file's
    last witnessed stamp), never drop it and never fabricate an end."""
    art = str(tmp_path)
    job = "b" * 64
    code = (
        "import sys, time\n"
        "from tpusim.obs.trace import SpanRecorder, SPAN_DISPATCH\n"
        "r = SpanRecorder(sys.argv[1], 'worker-victim')\n"
        "r.begin(SPAN_DISPATCH, job=sys.argv[2], trace='tkill')\n"
        "print('ready', flush=True)\n"
        "time.sleep(60)\n"
    )
    child = subprocess.Popen(
        [sys.executable, "-c", code, art, job],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        assert child.stdout.readline().strip() == "ready"
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
            child.wait()

    spans, problems = obs_trace.stitch(art, job=job)
    assert problems == []
    assert len(spans) == 1
    s = spans[0]
    assert s["status"] == "abandoned"
    assert s["name"] == obs_trace.SPAN_DISPATCH
    assert s["trace"] == "tkill" and s["pid"] == child.pid
    assert s["end"] >= s["start"]
