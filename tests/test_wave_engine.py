"""The wave-batched engine (tpusim.sim.wave_engine) must be bit-identical to
the sequential oracle engine — its intra-wave row patching repairs every
conflict exactly, so there is no divergence to tolerate. Randomized
create/delete mixes over heterogeneous clusters pin the equivalence for
every table-izable policy and for wave sizes that do / don't divide the
event count."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import random_cluster, random_pods
from tests.test_table_engine import _assert_equal, _events_with_deletes
from tpusim.policies import make_policy
from tpusim.sim.engine import make_replay
from tpusim.sim.table_engine import build_pod_types
from tpusim.sim.wave_engine import make_wave_replay


@pytest.mark.parametrize(
    "policy,gpu_sel",
    [
        ("FGDScore", "FGDScore"),
        ("BestFitScore", "best"),
        ("GpuPackingScore", "worst"),
        ("GpuClusteringScore", "best"),
        ("DotProductScore", "DotProductScore"),
        ("PWRScore", "PWRScore"),
        ("Simon", "best"),
    ],
    ids=lambda p: str(p),
)
def test_wave_engine_matches_sequential(policy, gpu_sel):
    rng = np.random.default_rng(7)
    state, tp = random_cluster(rng, num_nodes=24)
    pods = random_pods(rng, num_pods=60)
    ev_kind, ev_pod = _events_with_deletes(60, rng)
    policies = [(make_policy(policy), 1000)]
    key = jax.random.PRNGKey(3)
    rank = jnp.asarray(rng.permutation(24).astype(np.int32))

    seq = make_replay(policies, gpu_sel=gpu_sel, report=False)
    r0 = seq(state, pods, ev_kind, ev_pod, tp, key, rank)
    wav = make_wave_replay(policies, gpu_sel=gpu_sel, wave=8)
    r1 = wav(state, pods, build_pod_types(pods), ev_kind, ev_pod, tp, key, rank)
    _assert_equal(r0, r1)
    assert np.array_equal(np.asarray(r0.event_node), np.asarray(r1.event_node))
    assert np.array_equal(np.asarray(r0.event_dev), np.asarray(r1.event_dev))


@pytest.mark.parametrize("wave", [1, 3, 8, 16, 17])
def test_wave_sizes_all_equal(wave):
    """Every W gives the oracle's placements — W is purely a throughput
    knob, including sizes that don't divide the event count (internal
    EV_SKIP padding)."""
    rng = np.random.default_rng(19)
    state, tp = random_cluster(rng, num_nodes=20)
    pods = random_pods(rng, num_pods=45)
    ev_kind, ev_pod = _events_with_deletes(45, rng)
    policies = [(make_policy("FGDScore"), 1000)]
    key = jax.random.PRNGKey(4)
    rank = jnp.asarray(rng.permutation(20).astype(np.int32))

    seq = make_replay(policies, gpu_sel="FGDScore", report=False)
    r0 = seq(state, pods, ev_kind, ev_pod, tp, key, rank)
    wav = make_wave_replay(policies, gpu_sel="FGDScore", wave=wave)
    r1 = wav(state, pods, build_pod_types(pods), ev_kind, ev_pod, tp, key, rank)
    _assert_equal(r0, r1)
    assert np.array_equal(np.asarray(r0.event_node), np.asarray(r1.event_node))


def test_wave_engine_weighted_multi_policy():
    """Two weighted score plugins (the reference's PWR+FGD mixes)."""
    rng = np.random.default_rng(11)
    state, tp = random_cluster(rng, num_nodes=16)
    pods = random_pods(rng, num_pods=40)
    ev_kind, ev_pod = _events_with_deletes(40, rng)
    policies = [(make_policy("PWRScore"), 500), (make_policy("FGDScore"), 500)]
    key = jax.random.PRNGKey(5)
    rank = jnp.asarray(rng.permutation(16).astype(np.int32))

    seq = make_replay(policies, gpu_sel="FGDScore", report=False)
    r0 = seq(state, pods, ev_kind, ev_pod, tp, key, rank)
    wav = make_wave_replay(policies, gpu_sel="FGDScore", wave=8)
    r1 = wav(state, pods, build_pod_types(pods), ev_kind, ev_pod, tp, key, rank)
    _assert_equal(r0, r1)


def test_wave_engine_pinned_pods():
    """nodeSelector-pinned pods stay a per-event feasibility mask; the
    intra-wave fresh patching must not lose the pinning term."""
    rng = np.random.default_rng(13)
    state, tp = random_cluster(rng, num_nodes=8)
    pods = random_pods(rng, num_pods=12)
    pinned = np.full(12, -1, np.int32)
    pinned[3] = 5
    pinned[7] = 2
    pods = pods._replace(pinned=jnp.asarray(pinned))
    ev_kind = jnp.zeros(12, jnp.int32)
    ev_pod = jnp.arange(12, dtype=jnp.int32)
    policies = [(make_policy("FGDScore"), 1000)]
    key = jax.random.PRNGKey(1)

    seq = make_replay(policies, gpu_sel="FGDScore", report=False)
    r0 = seq(state, pods, ev_kind, ev_pod, tp, key)
    wav = make_wave_replay(policies, gpu_sel="FGDScore", wave=4)
    r1 = wav(state, pods, build_pod_types(pods), ev_kind, ev_pod, tp, key)
    _assert_equal(r0, r1)
    placed = np.asarray(r1.placed_node)
    assert placed[3] in (5, -1) and placed[7] in (2, -1)


def test_wave_engine_hot_node_contention():
    """Identical pods that the oracle packs onto one node back-to-back (the
    41% consecutive-same-node pattern of the openb FGD replay) exercise the
    intra-wave patch path on every slot."""
    from tpusim.types import PodSpec, make_node_state

    state = make_node_state(
        cpu_cap=[64000] * 4, mem_cap=[262144] * 4,
        gpu_cnt=[8] * 4, gpu_type=[1] * 4,
    )
    _, tp = random_cluster(np.random.default_rng(0), num_nodes=4)
    num = 24
    pods = PodSpec(
        cpu=jnp.full(num, 2000, jnp.int32),
        mem=jnp.full(num, 4096, jnp.int32),
        gpu_milli=jnp.full(num, 500, jnp.int32),
        gpu_num=jnp.ones(num, jnp.int32),
        gpu_mask=jnp.zeros(num, jnp.int32),
        pinned=jnp.full(num, -1, jnp.int32),
    )
    ev_kind = jnp.zeros(num, jnp.int32)
    ev_pod = jnp.arange(num, dtype=jnp.int32)
    policies = [(make_policy("GpuPackingScore"), 1000)]
    key = jax.random.PRNGKey(8)

    seq = make_replay(policies, gpu_sel="best", report=False)
    r0 = seq(state, pods, ev_kind, ev_pod, tp, key)
    wav = make_wave_replay(policies, gpu_sel="best", wave=8)
    r1 = wav(state, pods, build_pod_types(pods), ev_kind, ev_pod, tp, key)
    _assert_equal(r0, r1)
    # the packing policy must actually have packed consecutively (the
    # contention this test exists to exercise)
    en = np.asarray(r0.event_node)
    assert (en[1:] == en[:-1]).any()


def test_wave_engine_rejects_randomized():
    with pytest.raises(ValueError):
        make_wave_replay([(make_policy("RandomScore"), 1000)])
    with pytest.raises(ValueError):
        make_wave_replay([(make_policy("FGDScore"), 1000)], gpu_sel="random")
    with pytest.raises(ValueError):
        make_wave_replay([(make_policy("FGDScore"), 1000)], wave=0)
