"""Fault injection (ISSUE 2): NodeFail/NodeRecover/Evict replay, the
retry/backoff requeue, terminal UnscheduledPod state, and the determinism
acceptance criteria — identical disruption metrics for identical seeds,
and NodeFail → retry → reschedule landing a pod on a DIFFERENT node."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpusim.io.trace import NodeRow, PodRow
from tpusim.sim.driver import Simulator, SimulatorConfig, validate_events
from tpusim.sim.engine import EV_EVICT, EV_NODE_FAIL, EV_NODE_RECOVER
from tpusim.sim.faults import (
    FaultConfig,
    FaultEvent,
    fail_node,
    generate_fault_schedule,
    is_down,
    recover_node,
    validate_fault_schedule,
)
from tpusim.sim.queues import RetryQueue

# metric-free by default: the per-event report path compiles its own
# post-pass per segment shape, and one test (the evict one) covering it
# under faults is enough for the tier-1 budget
CFG = dict(
    policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
    report_per_event=False,
)


def _sim(nodes, pods, **over):
    sim = Simulator(nodes, SimulatorConfig(**{**CFG, **over}))
    sim.set_workload_pods(pods)
    sim.set_typical_pods()
    return sim


def _two_nodes():
    return [
        NodeRow("host-a", 16000, 65536, 2, "V100M16"),
        NodeRow("host-b", 16000, 65536, 2, "V100M16"),
    ]


def _share_pods(n):
    return [PodRow(f"p{i}", 2000, 1024, 1, 500) for i in range(n)]


# ---- retry queue ----


def test_retry_queue_backoff_caps():
    rq = RetryQueue(base=8, cap=100, max_retries=5)
    assert [rq.backoff(k) for k in (1, 2, 3, 4, 5)] == [8, 16, 32, 64, 100]


def test_retry_queue_terminal_after_max_retries():
    rq = RetryQueue(base=2, cap=16, max_retries=2)
    assert rq.push(7, 0, 1) == 2
    assert rq.push(7, 2, 2) == 6
    assert rq.push(7, 6, 3) is None  # out of retries -> dead list
    assert rq.dead == [(7, 2)]


def test_retry_queue_fifo_among_same_position():
    rq = RetryQueue(base=4, cap=4, max_retries=3)
    for pod in (3, 1, 2):
        rq.push(pod, 0, 1)
    assert rq.next_ready() == 4
    assert [p for p, _ in rq.pop_due(4)] == [3, 1, 2]  # insertion order
    assert len(rq) == 0 and rq.pop_due(100) == []


# ---- fault state transitions ----


def test_fail_and_recover_node_state():
    from tpusim.types import make_node_state

    state = make_node_state(
        cpu_cap=[8000, 8000], mem_cap=[4096, 4096], gpu_cnt=[2, 2],
        gpu_type=[0, 0],
    )
    down = fail_node(state, 0)
    assert bool(is_down(down)[0]) and not bool(is_down(down)[1])
    # down encoding must be filter-infeasible for ANY pod, even 0-request
    from tpusim.sim.step import filter_nodes
    from tpusim.types import make_pod

    feas = filter_nodes(down, make_pod(cpu=0, mem=0))
    assert not bool(feas[0]) and bool(feas[1])
    back = recover_node(down, 0)
    assert not bool(is_down(back)[0])
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(state)):
        assert np.array_equal(np.asarray(a), np.asarray(b))  # empty again


def test_generated_schedule_deterministic_and_valid():
    cfg = FaultConfig(mtbf_events=5, mttr_events=7, evict_every_events=11,
                      seed=9)
    a = generate_fault_schedule(6, 200, cfg)
    b = generate_fault_schedule(6, 200, cfg)
    assert a == b and len(a) > 0
    validate_fault_schedule(a, 6, 100)
    assert all(e.pos == sorted(x.pos for x in a)[i] for i, e in enumerate(a))


def test_validate_fault_schedule_rejects_bad_targets():
    with pytest.raises(ValueError, match="node 5 out of range"):
        validate_fault_schedule(
            [FaultEvent(0, EV_NODE_FAIL, node=5)], 2, 10
        )
    with pytest.raises(ValueError, match="kind"):
        validate_fault_schedule([FaultEvent(0, 99)], 2, 10)


# ---- run_events validation satellite ----


def test_run_events_rejects_fault_kinds_and_bad_indices():
    """Fault kinds and out-of-range pod indices must raise at run_events
    entry instead of becoming silent no-op scatters under jit."""
    nodes = _two_nodes()
    pods = _share_pods(3)
    sim = _sim(nodes, pods)
    from tpusim.io.trace import pods_to_specs

    specs = pods_to_specs(pods)
    key = jax.random.PRNGKey(0)
    with pytest.raises(ValueError, match="unknown kind"):
        sim.run_events(
            sim.init_state, specs, jnp.asarray([0, EV_NODE_FAIL], jnp.int32),
            jnp.asarray([0, 1], jnp.int32), key,
        )
    with pytest.raises(ValueError, match="out of range"):
        sim.run_events(
            sim.init_state, specs, jnp.zeros(2, jnp.int32),
            jnp.asarray([0, 3], jnp.int32), key,
        )
    with pytest.raises(ValueError, match="shape mismatch"):
        validate_events(np.zeros(2, np.int32), np.zeros(3, np.int32), 5)


# ---- end-to-end fault replay ----


@pytest.mark.slow  # tier-1 trim, ISSUE 16: rides resume-smoke
def test_nodefail_retry_reschedules_on_different_node():
    """The acceptance scenario: a pod placed on host-a loses its node,
    waits out its backoff in the retry queue while the trace continues,
    and re-lands MID-TRACE on host-b with a positive reschedule latency."""
    nodes = _two_nodes()
    # p0 is the GPU pod under test; p1..p3 are cpu-only filler that keeps
    # the trace running past the retry's ready position
    pods = [PodRow("p0", 2000, 1024, 1, 500)] + [
        PodRow(f"f{i}", 1000, 512, 0, 0) for i in range(3)
    ]
    sim = _sim(nodes, pods)
    first = int(sim.schedule_pods(pods).placed_node[0])

    sim2 = _sim(nodes, pods)
    res = sim2.schedule_pods_with_faults(
        pods,
        faults=[FaultEvent(pos=1, kind=EV_NODE_FAIL, node=first)],
        fault_cfg=FaultConfig(backoff_base=2, backoff_cap=8),
    )
    dm = sim2.last_disruption
    assert dm.node_failures == 1 and dm.evicted_pods == 1
    assert dm.rescheduled_pods == 1
    # the pod re-landed, on the OTHER host, 1 + backoff events later
    assert int(res.placed_node[0]) >= 0
    assert int(res.placed_node[0]) != first
    assert dm.reschedule_latency_events == [2]


def test_fault_replay_deterministic_under_seed():
    """Two runs of the same MTBF seed must agree on every placement and
    every disruption number (the pinned determinism criterion)."""
    nodes = _two_nodes()
    pods = _share_pods(6)
    fcfg = FaultConfig(mtbf_events=3, mttr_events=4, evict_every_events=5,
                       seed=5, backoff_base=2, backoff_cap=8, max_retries=2)
    sims = [_sim(nodes, pods) for _ in range(2)]
    results = [s.schedule_pods_with_faults(pods, fault_cfg=fcfg)
               for s in sims]
    assert np.array_equal(results[0].placed_node, results[1].placed_node)
    assert np.array_equal(results[0].dev_mask, results[1].dev_mask)
    a, b = (s.last_disruption for s in sims)
    assert a.as_dict() == b.as_dict()
    assert a.reschedule_latency_events == b.reschedule_latency_events
    # the [Disruption] block made it into the log + the direct-CSV stash
    assert any("[Disruption]" in l for l in sims[0].log.lines)
    assert any(k.startswith("disruption_")
               for k in sims[0].analysis_summary)


def test_max_retries_terminal_unscheduled():
    """A pod whose only feasible host never comes back burns its retries
    and lands in the terminal UnscheduledPod state with the dedicated
    reason."""
    nodes = [NodeRow("only", 16000, 65536, 2, "V100M16")]
    pods = _share_pods(1)
    sim = _sim(nodes, pods)
    res = sim.schedule_pods_with_faults(
        pods,
        faults=[FaultEvent(pos=1, kind=EV_NODE_FAIL, node=0)],
        fault_cfg=FaultConfig(max_retries=2, backoff_base=2, backoff_cap=4),
    )
    dm = sim.last_disruption
    assert dm.unscheduled_after_retries == 1
    assert dm.retries_enqueued == 2  # both retries ran, both failed
    assert res.placed_node[0] == -1
    reasons = [u.reason for u in res.unscheduled_pods]
    assert reasons == ["max-retries-exceeded"]
    # permanent loss clocks dark capacity to end of trace: the failure
    # fired AT the last base event (pos 1 of a 1-event trace), so 0 here
    assert dm.failed_node_gpu_events == 0


def test_evict_event_requeues_and_reports():
    """A single-pod Evict preemption returns resources, requeues the pod,
    and the pod re-lands after its backoff — with per-event reporting on,
    so the fault segments exercise the report/metrics path too."""
    nodes = _two_nodes()
    pods = _share_pods(2)
    sim = _sim(nodes, pods, report_per_event=True)
    res = sim.schedule_pods_with_faults(
        pods,
        faults=[FaultEvent(pos=2, kind=EV_EVICT, pod=0)],
        fault_cfg=FaultConfig(backoff_base=2, backoff_cap=4),
    )
    dm = sim.last_disruption
    assert dm.evicted_pods == 1 and dm.rescheduled_pods == 1
    assert (res.placed_node >= 0).all()
    assert any("[Fault] pod p0 evicted" in l for l in sim.log.lines)


def test_recovery_frag_delta_and_gpu_events():
    """Fail + recover accounts the dark capacity window and records a
    post-recovery frag delta sample."""
    nodes = _two_nodes()
    pods = _share_pods(4)
    sim = _sim(nodes, pods)
    sim.schedule_pods_with_faults(
        pods,
        faults=[
            FaultEvent(pos=1, kind=EV_NODE_FAIL, node=0),
            FaultEvent(pos=3, kind=EV_NODE_RECOVER, node=0),
        ],
    )
    dm = sim.last_disruption
    assert dm.node_failures == 1 and dm.node_recoveries == 1
    assert dm.failed_node_gpu_events == 2 * (3 - 1)  # 2 GPUs x 2 events
    assert len(dm.post_recovery_frag_delta) == 1


def test_faults_rejects_timestamp_traces():
    nodes = _two_nodes()
    pods = _share_pods(2)
    sim = Simulator(nodes, SimulatorConfig(use_timestamps=True, **CFG))
    sim.set_workload_pods(pods)
    sim.set_typical_pods()
    with pytest.raises(ValueError, match="creation-ordered"):
        sim.schedule_pods_with_faults(pods)


def test_pallas_vmem_degrades_to_table(monkeypatch):
    """Graceful degradation: a forced pallas engine whose resident set
    cannot fit the VMEM budget falls back to the table engine with a
    [Degrade] warning — same placements, no death."""
    monkeypatch.setenv("TPUSIM_PALLAS_VMEM_BYTES", "1024")  # nothing fits
    nodes = _two_nodes()
    pods = _share_pods(4)

    def run(engine):
        sim = Simulator(nodes, SimulatorConfig(
            policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
            report_per_event=False, engine=engine,
        ))
        sim.set_workload_pods(pods)
        sim.set_typical_pods()
        from tpusim.io.trace import pods_to_specs

        specs = pods_to_specs(pods)
        out = sim.run_events(
            sim.init_state, specs, jnp.zeros(4, jnp.int32),
            jnp.arange(4, dtype=jnp.int32), jax.random.PRNGKey(0),
        )
        return sim, out

    sim_p, out_p = run("pallas")
    assert any("[Degrade]" in l and "VMEM" in l for l in sim_p.log.lines)
    assert sim_p._last_engine == "table"
    monkeypatch.delenv("TPUSIM_PALLAS_VMEM_BYTES")
    sim_t, out_t = run("table")
    assert np.array_equal(
        np.asarray(out_p.placed_node), np.asarray(out_t.placed_node)
    )


@pytest.mark.slow  # compiles its own chunked segment lengths
def test_fault_replay_composes_with_checkpointing(tmp_path):
    """The create/delete/fault-mix half of the resume acceptance: fault
    segments run through the normal run_events dispatch, so a fault replay
    with checkpointing enabled must equal the unsegmented fault replay —
    placements AND disruption metrics."""
    nodes = _two_nodes()
    pods = _share_pods(6)
    fcfg = FaultConfig(mtbf_events=3, mttr_events=4, seed=5,
                       backoff_base=2, backoff_cap=8)
    sim_a = _sim(nodes, pods)
    ra = sim_a.schedule_pods_with_faults(pods, fault_cfg=fcfg)
    sim_b = _sim(nodes, pods, checkpoint_every=2,
                 checkpoint_dir=str(tmp_path))
    rb = sim_b.schedule_pods_with_faults(pods, fault_cfg=fcfg)
    assert np.array_equal(ra.placed_node, rb.placed_node)
    assert np.array_equal(ra.dev_mask, rb.dev_mask)
    assert sim_a.last_disruption.as_dict() == sim_b.last_disruption.as_dict()


@pytest.mark.slow  # tier-1 trim, ISSUE 16: rides resume-smoke
def test_retry_budget_resets_on_successful_reschedule():
    """max_retries bounds CONSECUTIVE failures: a pod evicted more than
    max_retries separate times, rescheduling successfully in between, must
    never be terminally killed by accumulation."""
    nodes = _two_nodes()
    pods = [PodRow("p0", 2000, 1024, 1, 500)] + [
        PodRow(f"f{i}", 1000, 512, 0, 0) for i in range(6)
    ]
    sim = _sim(nodes, pods)
    res = sim.schedule_pods_with_faults(
        pods,
        faults=[FaultEvent(pos=p, kind=EV_EVICT, pod=0) for p in (1, 3, 5)],
        fault_cfg=FaultConfig(max_retries=2, backoff_base=1, backoff_cap=1),
    )
    dm = sim.last_disruption
    assert dm.evicted_pods == 3 and dm.rescheduled_pods == 3
    assert dm.unscheduled_after_retries == 0
    assert int(res.placed_node[0]) >= 0
    assert res.unscheduled_pods == []
