"""The no-shared-fs transfer plane (ISSUE 13): digest-verified trace
download with partial-transfer resume, signed-result upload with
torn/forged rejection, the remote lease mirror, retrying POSTs, and
multi-trace hosting — the tier-1 slice is pure protocol over loopback
HTTP (no device dispatch, no compiles). The process-spawning acceptance
(remote workers + kill -9 + crash-loop under a flaky WAN shim) is the
slow-marked `make fleet-wan-smoke` harness at the bottom.
"""

import json
import os
import threading
import time

import pytest

from tpusim.obs.gate import _write_fleet_trace
from tpusim.svc import jobs as svc_jobs
from tpusim.svc import leases as svc_leases
from tpusim.svc.api import JobService, start_job_server
from tpusim.svc.batcher import JobQueue
from tpusim.svc.client import ServiceError, _request
from tpusim.svc.fleet import (
    _get_bytes,
    _part_path,
    _post,
    _post_bytes,
    ensure_local_trace,
    fetch_trace_file,
    new_transfer_counters,
    resolve_worker_mode,
)
from tpusim.svc.worker import TraceRef, load_trace

FAM = [["FGDScore", 1000], ["BestFitScore", 500]]

# the flight recorder (ISSUE 19) deliberately writes into the artifact
# dir on REJECTED requests too — the audit chain records the 400 and
# the span plane owns spans/ — so "untouched" means "no payload files"
_OBS_FILES = {"spans", "audit.jsonl", "audit.jsonl.head",
              "tsdb.snapshot.json"}


def _payload_files(art):
    return [f for f in os.listdir(art) if f not in _OBS_FILES]


@pytest.fixture()
def stack(tmp_path):
    """A real-HTTP fleet coordinator hosting one file-backed trace, no
    workers, no recovery — the transfer plane's server half."""
    base = str(tmp_path)
    nodes_csv, pods_csv = _write_fleet_trace(base)
    trace = load_trace("default", nodes_csv, pods_csv)
    art = os.path.join(base, "art")
    os.makedirs(art)
    srv, service, _ = start_job_server(
        art, {"default": trace}, listen=":0", fleet=True,
        start_worker=False, recover=False,
    )
    yield srv, service, trace, base
    srv.stop()


def _trace_meta(url, name):
    code, _, meta = _request(f"{url}/traces/{name}")
    assert code == 200
    return meta


# ---------------------------------------------------------------------------
# trace download: cache, resume, re-download on mismatch
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_trace_listing_and_meta(stack):
    srv, service, trace, base = stack
    code, _, doc = _request(srv.url + "/traces")
    assert code == 200
    meta = doc["traces"]["default"]
    assert meta["digest"] == trace.digest
    assert meta["nodes_sha256"] == trace.nodes_sha256
    assert meta["pods_bytes"] == trace.pods_bytes > 0
    # per-name meta matches the listing; unknown names 404 loudly
    assert _trace_meta(srv.url, "default") == meta
    code, _, err = _request(srv.url + "/traces/ghost")
    assert code == 404 and "ghost" in err["error"]
    code, _, err = _request(srv.url + "/traces/default/virus.exe")
    assert code == 404


def test_download_cache_and_digest_verify(stack):
    srv, service, trace, base = stack
    meta = _trace_meta(srv.url, "default")
    cache = os.path.join(base, "wcache")
    counters = new_transfer_counters()
    t = ensure_local_trace(srv.url, "default", meta, cache,
                           counters=counters)
    assert t.digest == trace.digest
    assert counters["downloads"] == 2  # nodes.csv + pods.csv
    assert counters["download_bytes"] == (
        trace.nodes_bytes + trace.pods_bytes
    )
    ddir = os.path.join(cache, "traces", trace.digest)
    assert sorted(os.listdir(ddir)) == ["nodes.csv", "pods.csv"]
    # second acquisition: pure cache hit, zero HTTP
    c2 = new_transfer_counters()
    t2 = ensure_local_trace(srv.url, "default", meta, cache,
                            counters=c2)
    assert t2.digest == trace.digest and c2["downloads"] == 0


@pytest.mark.slow
def test_partial_download_resumes(stack):
    """A dead transfer's .part file is resumed with a Range request —
    the re-download starts where the last one died, and the finished
    file still sha-verifies."""
    srv, service, trace, base = stack
    cache = os.path.join(base, "wcache", "traces", trace.digest)
    os.makedirs(cache)
    dest = os.path.join(cache, "nodes.csv")
    with open(trace.nodes_csv, "rb") as f:
        full = f.read()
    half = len(full) // 2
    with open(_part_path(dest), "wb") as f:
        f.write(full[:half])
    counters = new_transfer_counters()
    fetch_trace_file(
        srv.url, "/traces/default/nodes.csv", dest,
        trace.nodes_sha256, counters=counters,
    )
    assert counters["resumed"] == 1
    # only the missing suffix crossed the wire
    assert counters["download_bytes"] == len(full) - half
    with open(dest, "rb") as f:
        assert f.read() == full
    assert not os.path.exists(_part_path(dest))


@pytest.mark.slow
def test_range_request_answers_206(stack):
    srv, service, trace, base = stack
    code, headers, data = _get_bytes(
        srv.url, "/traces/default/nodes.csv", offset=10
    )
    assert code == 206
    assert headers.get("Content-Range", "").startswith("bytes 10-")
    with open(trace.nodes_csv, "rb") as f:
        assert data == f.read()[10:]
    # an offset past EOF is 416, not silent garbage
    code, _, _ = _get_bytes(
        srv.url, "/traces/default/nodes.csv", offset=10 ** 9
    )
    assert code == 416


@pytest.mark.slow
def test_corrupt_cache_forces_redownload(stack):
    srv, service, trace, base = stack
    meta = _trace_meta(srv.url, "default")
    cache = os.path.join(base, "wcache")
    ensure_local_trace(srv.url, "default", meta, cache)
    dest = os.path.join(cache, "traces", trace.digest, "nodes.csv")
    with open(dest, "w") as f:
        f.write("sn,cpu_milli\nbitrot,1\n")  # corrupt the cached copy
    counters = new_transfer_counters()
    t = ensure_local_trace(srv.url, "default", meta, cache,
                           counters=counters)
    assert t.digest == trace.digest  # healed
    assert counters["sha_retries"] == 1 and counters["downloads"] == 1


@pytest.mark.slow
def test_sha_skew_fails_loudly(stack):
    """The coordinator advertising a sha its bytes do not match (version
    skew, a lying proxy): one clean re-download, then a LOUD refusal —
    never parsing unverified bytes."""
    srv, service, trace, base = stack
    dest = os.path.join(base, "skew", "nodes.csv")
    os.makedirs(os.path.dirname(dest))
    counters = new_transfer_counters()
    with pytest.raises(ServiceError, match="sha256 still mismatches"):
        fetch_trace_file(
            srv.url, "/traces/default/nodes.csv", dest, "f" * 64,
            counters=counters,
        )
    assert counters["sha_retries"] == 2
    assert not os.path.exists(dest)  # nothing half-landed


# ---------------------------------------------------------------------------
# result upload: torn/forged rejected, atomic landing, restart retry
# ---------------------------------------------------------------------------


def _result_fixture(tmp_path, digest):
    scratch = os.path.join(str(tmp_path), "scratch")
    svc_jobs.write_result(scratch, digest, {
        "job": digest, "placed": 7, "placed_node": [0, 1, 2],
    })
    data = svc_jobs.result_bytes(scratch, digest)
    assert data is not None
    return data


@pytest.mark.slow
def test_torn_upload_rejected_keeps_no_partial(stack, tmp_path):
    srv, service, trace, base = stack
    digest = "a" * 64
    data = _result_fixture(tmp_path, digest)
    art = service.artifact_dir

    # truncated mid-transfer: 400, artifact dir untouched
    code, _, err = _post_bytes(srv.url, f"/results/{digest}",
                               data[:-20])
    assert code == 400 and "rejected upload" in err["error"]
    assert _payload_files(art) == []

    # edited payload under the old header digest: forged, 400
    lines = data.decode().split("\n")
    doc = json.loads(lines[1])
    doc["placed"] = 9999
    forged = (lines[0] + "\n" + json.dumps(doc) + "\n").encode()
    code, _, err = _post_bytes(srv.url, f"/results/{digest}", forged)
    assert code == 400
    # valid bytes under the WRONG digest: foreign, 400
    code, _, err = _post_bytes(srv.url, f"/results/{'b' * 64}", data)
    assert code == 400 and "foreign" in err["error"]
    assert _payload_files(art) == []

    # the real bytes land byte-identically and idempotently
    code, _, ok = _post_bytes(srv.url, f"/results/{digest}", data)
    assert code == 200 and ok["stored"] == digest
    with open(svc_jobs.result_path(art, digest), "rb") as f:
        assert f.read() == data
    assert svc_jobs.find_result(art, digest)["placed"] == 7
    code, _, _ = _post_bytes(srv.url, f"/results/{digest}", data)
    assert code == 200  # duplicate upload: idempotent replace
    with open(svc_jobs.result_path(art, digest), "rb") as f:
        assert f.read() == data
    assert [f for f in _payload_files(art) if f.endswith(".tmp")] == []

    # the rejection counters are visible in /queue's transfer block
    code, _, q = _request(srv.url + "/queue")
    assert q["transfer"]["uploads_rejected"] == 3
    assert q["transfer"]["uploads_ok"] == 2


@pytest.mark.slow  # tears down and respawns the HTTP stack mid-test —
# the slowest transfer slice; resume-smoke runs it (ISSUE 16 budget
# buy-back)
def test_upload_retried_across_coordinator_restart(stack, tmp_path):
    """The satellite's restart case: an upload retried against a
    RESTARTED coordinator (same artifact dir) yields byte-identical
    signed results — content addressing makes the retry a no-op
    replace."""
    srv, service, trace, base = stack
    digest = "c" * 64
    data = _result_fixture(tmp_path, digest)
    art = service.artifact_dir
    code, _, _ = _post_bytes(srv.url, f"/results/{digest}", data)
    assert code == 200
    srv.stop()

    # "restart": a fresh coordinator over the same artifact dir
    srv2, service2, _ = start_job_server(
        art, {"default": trace}, listen=":0", fleet=True,
        start_worker=False, recover=False,
    )
    try:
        code, _, _ = _post_bytes(srv2.url, f"/results/{digest}", data)
        assert code == 200
        with open(svc_jobs.result_path(art, digest), "rb") as f:
            assert f.read() == data
    finally:
        srv2.stop()


# ---------------------------------------------------------------------------
# retrying POSTs + the lease mirror + mode resolution
# ---------------------------------------------------------------------------


class _DropFirst:
    """Shim app: answer 503 + Retry-After: 0 for the first N matching
    requests, then fall through to the real app."""

    def __init__(self, path_prefix, n):
        self.path_prefix = path_prefix
        self.left = n
        self.dropped = 0

    def handle(self, method, path, body, headers=None):
        if path.startswith(self.path_prefix) and self.left > 0:
            self.left -= 1
            self.dropped += 1
            return (503, "application/json",
                    b'{"error": "injected fault"}\n',
                    {"Retry-After": "0"})
        return None


@pytest.mark.slow
def test_post_rides_backoff_past_503(stack):
    """Satellite 1: fleet POSTs retry 429/5xx on the shared backoff
    schedule honoring Retry-After — three injected 503s cost three
    zero-delay retries, not a dead worker."""
    srv, service, trace, base = stack
    shim = _DropFirst("/workers/register", 3)
    srv._apps.insert(0, shim)
    code, _, reg = _post(srv.url, "/workers/register",
                         {"worker": "wx", "pid": 1, "host": "h"})
    assert code == 200 and reg["worker"] == "wx"
    assert shim.dropped == 3
    # exhausted budget: the final 503 surfaces instead of hanging
    shim2 = _DropFirst("/workers/claim", 99)
    srv._apps.insert(0, shim2)
    code, _, _ = _post(srv.url, "/workers/claim", {"worker": "wx"},
                       max_attempts=2)
    assert code == 503 and shim2.dropped == 2


@pytest.mark.slow
def test_post_backoff_aborts_on_stop_event(stack):
    """A SIGTERM'd worker must not ride out the whole backoff schedule
    against a draining coordinator's 503 + Retry-After answers — the
    stop event surfaces the last answer at once (the drain-latency
    regression of the retrying _post)."""
    srv, service, trace, base = stack
    srv.begin_drain()  # every POST now answers 503 + Retry-After: 2
    try:
        stop = threading.Event()
        stop.set()
        t0 = time.monotonic()
        code, _, _ = _post(srv.url, "/workers/claim", {"worker": "wz"},
                           stop_event=stop)
        elapsed = time.monotonic() - t0
        assert code == 503
        assert elapsed < 1.0  # one request, zero 2 s Retry-After waits
    finally:
        srv._draining = False


@pytest.mark.slow
def test_lease_mirror_stake_release(stack):
    srv, service, trace, base = stack
    art = service.artifact_dir
    members = ["d" * 64, "e" * 64]
    code, _, doc = _post(srv.url, "/leases", {
        "op": "stake", "worker": "w9", "pid": 321, "members": members,
    })
    assert code == 200 and doc["staked"] == 2
    assert doc["deadline_unix"] > time.time()
    got = dict(svc_leases.scan_leases(art))
    assert sorted(got) == members
    assert got["d" * 64]["worker"] == "w9"
    assert got["d" * 64]["pid"] == 321
    assert got["d" * 64]["members"] == members
    code, _, doc = _post(srv.url, "/leases",
                         {"op": "release", "worker": "w9",
                          "members": members})
    assert code == 200 and doc["released"] == 2
    assert svc_leases.scan_leases(art) == []
    # malformed bodies are loud
    code, _, err = _post(srv.url, "/leases", {"op": "stake"})
    assert code == 400
    code, _, err = _post(srv.url, "/leases",
                         {"op": "destroy", "members": ["x"]})
    assert code == 400 and "stake|release" in err["error"]


def test_wire_strings_cannot_traverse_paths(stack, tmp_path):
    """Digests and lease members arrive off the wire and become file
    stems under the artifact dir — traversal payloads must die at the
    endpoint, and a non-object header must be a clean 400 (not a
    retryable 500)."""
    srv, service, trace, base = stack
    art = service.artifact_dir
    evil = "../" * 6 + "tmp/evil"
    code, _, err = _post(srv.url, "/leases", {
        "op": "stake", "worker": "w", "pid": 1, "members": [evil],
    })
    assert code == 400 and "not job digests" in err["error"]
    code, _, _ = _post(srv.url, "/leases", {
        "op": "release", "worker": "w", "members": [evil],
    })
    assert code == 400
    # uppercase/semi-plausible stems are rejected too (digests are
    # lowercase hex)
    code, _, _ = _post(srv.url, "/leases", {
        "op": "stake", "worker": "w", "pid": 1, "members": ["EVIL" * 16],
    })
    assert code == 400
    assert _payload_files(art) == []

    # a JSON-array header line: clean 400, counted as a rejection
    code, _, err = _post_bytes(srv.url, f"/results/{'a' * 64}",
                               b"[]\n{}\n")
    assert code == 400 and "rejected upload" in err["error"]
    assert _payload_files(art) == []


@pytest.mark.slow
def test_orphan_part_adopted_across_respawn(stack):
    """A kill -9'd predecessor's .part (different, DEAD pid) is adopted
    and resumed by the successor — crash-resume reaches across a
    respawn instead of leaking parts and restarting from byte 0."""
    from tpusim.svc.fleet import _adopt_orphan_part

    srv, service, trace, base = stack
    cache = os.path.join(base, "wcache", "traces", trace.digest)
    os.makedirs(cache)
    dest = os.path.join(cache, "nodes.csv")
    with open(trace.nodes_csv, "rb") as f:
        full = f.read()
    # a dead pid's partial download (pids are bounded well below 2**22)
    dead_pid = 2 ** 22 + 12345
    orphan = f"{dest}.{dead_pid}.part"
    with open(orphan, "wb") as f:
        f.write(full[: len(full) // 2])
    smaller = f"{dest}.{dead_pid + 1}.part"
    with open(smaller, "wb") as f:
        f.write(full[:4])
    counters = new_transfer_counters()
    fetch_trace_file(
        srv.url, "/traces/default/nodes.csv", dest,
        trace.nodes_sha256, counters=counters,
    )
    assert counters["resumed"] == 1
    # only the adopted orphan's missing suffix crossed the wire
    assert counters["download_bytes"] == len(full) - len(full) // 2
    with open(dest, "rb") as f:
        assert f.read() == full
    # every .part is gone: adopted/renamed or cleaned
    assert [p for p in os.listdir(cache) if p.endswith(".part")] == []

    # a COMPLETE orphaned part (died between write and rename): zero
    # bytes transferred, just renamed into place
    os.unlink(dest)
    with open(f"{dest}.{dead_pid}.part", "wb") as f:
        f.write(full)
    c2 = new_transfer_counters()
    fetch_trace_file(
        srv.url, "/traces/default/nodes.csv", dest,
        trace.nodes_sha256, counters=c2,
    )
    assert c2["downloads"] == 0 and c2["download_bytes"] == 0
    with open(dest, "rb") as f:
        assert f.read() == full


@pytest.mark.slow
def test_resolve_worker_mode(stack):
    srv, service, trace, base = stack
    code, _, reg = _post(srv.url, "/workers/register",
                         {"worker": "wm", "pid": 2, "host": "h"})
    # same machine: every path readable -> auto picks shared-fs
    assert resolve_worker_mode("auto", reg) == "shared-fs"
    assert resolve_worker_mode("", reg) == "shared-fs"
    # explicit modes pass through
    assert resolve_worker_mode("remote", reg) == "remote"
    assert resolve_worker_mode("shared-fs", reg) == "shared-fs"
    # unreachable artifact dir or trace CSVs -> remote
    gone = dict(reg, artifact_dir="/no/such/dir")
    assert resolve_worker_mode("auto", gone) == "remote"
    skew = dict(reg, traces={
        "default": dict(reg["traces"]["default"],
                        nodes_csv="/no/such/nodes.csv"),
    })
    assert resolve_worker_mode("auto", skew) == "remote"
    with pytest.raises(ValueError, match="worker mode"):
        resolve_worker_mode("wan", reg)


@pytest.mark.slow
def test_register_records_mode_and_transfers(stack):
    srv, service, trace, base = stack
    _post(srv.url, "/workers/register",
          {"worker": "wr", "pid": 3, "host": "h", "mode": "remote"})
    counters = new_transfer_counters()
    counters["uploads"] = 4
    _post(srv.url, "/workers/complete",
          {"worker": "wr", "done": [], "failed": {},
           "transfers": counters})
    code, _, doc = _request(srv.url + "/workers")
    row = doc["workers"]["wr"]
    assert row["mode"] == "remote"
    assert row["transfers"]["uploads"] == 4
    # /queue's worker rows carry the same topology view
    code, _, q = _request(srv.url + "/queue")
    assert q["workers"]["wr"]["mode"] == "remote"


# ---------------------------------------------------------------------------
# multi-trace hosting (protocol level — no device)
# ---------------------------------------------------------------------------


def test_parse_trace_arg():
    from tpusim.cli import parse_trace_arg

    assert parse_trace_arg("alt=n.csv:p.csv") == ("alt", "n.csv",
                                                  "p.csv", 0)
    assert parse_trace_arg("alt=n.csv:p.csv:500") == ("alt", "n.csv",
                                                      "p.csv", 500)
    for bad in ("alt", "=n.csv:p.csv", "alt=n.csv", "alt=:p.csv",
                "alt=n.csv:p.csv:many"):
        with pytest.raises(ValueError, match="--trace"):
            parse_trace_arg(bad)


def test_multi_trace_batching_stays_per_trace(tmp_path):
    """Two hosted traces: jobs keep their (trace, family) shard — one
    claim never mixes traces — and unknown trace names 400 loudly."""
    import numpy as np

    from tpusim.io.trace import NodeRow, PodRow

    rng = np.random.default_rng(5)
    mk = lambda tag, n: TraceRef(  # noqa: E731
        tag,
        [NodeRow(f"{tag}{i}", 32000, 131072, int(g),
                 "V100M16" if g else "")
         for i, g in enumerate(rng.choice([0, 2, 4], n))],
        [PodRow(f"p{tag}{i}", 1000, 2048, 1, 500) for i in range(6)],
        "",
    )
    a, b = mk("a", 6), mk("b", 8)
    a = TraceRef(a.name, a.nodes, a.pods,
                 svc_jobs.trace_digest(a.nodes, a.pods))
    b = TraceRef(b.name, b.nodes, b.pods,
                 svc_jobs.trace_digest(b.nodes, b.pods))
    queue = JobQueue(maxsize=16, lane_width=8)
    service = JobService(queue, None, {"a": a, "b": b}, str(tmp_path))
    for i, tr in enumerate(["a", "b", "a", "b", "a"]):
        service.submit_payload(
            {"trace": tr, "policies": FAM,
             "weights": [1000 + i, 500], "seed": 42}
        )
    batch1 = queue.claim_batch("w", timeout=0)
    assert [j.spec.trace for j in batch1] == ["a", "a", "a"]
    batch2 = queue.claim_batch("w", timeout=0)
    assert [j.spec.trace for j in batch2] == ["b", "b"]
    with pytest.raises(ValueError, match="unknown trace"):
        service.submit_payload(
            {"trace": "ghost", "policies": FAM,
             "weights": [1, 1], "seed": 1}
        )


# ---------------------------------------------------------------------------
# slow: remote worker end-to-end + the WAN chaos acceptance
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_remote_worker_end_to_end(tmp_path):
    """One remote-mode worker joined over HTTP with NO shared paths:
    trace downloaded + digest-verified, the batch dispatched, signed
    results UPLOADED and landed on the coordinator's disk, lease files
    mirrored coordinator-side, /workers showing mode=remote with live
    transfer counters."""
    from tpusim.svc.fleet import run_worker

    base = str(tmp_path)
    nodes_csv, pods_csv = _write_fleet_trace(base)
    trace = load_trace("default", nodes_csv, pods_csv)
    art = os.path.join(base, "art")
    srv, service, _ = start_job_server(
        art, {"default": trace}, listen=":0", fleet=True,
        start_worker=False, recover=False, lane_width=2,
    )
    try:
        # single-policy family on purpose: gate.fleet_chaos_smoke
        # measures its COLD compile wall on the two-policy family over
        # this same synthetic trace shape — this test must not pre-warm
        # that jaxpr when both run in one process
        accepted = [
            service.submit_payload(
                {"policies": [["FGDScore", 1000]],
                 "weights": [1000 + i], "seed": 42,
                 "engine": "sequential"}
            )
            for i in range(2)
        ]
        stop = threading.Event()
        served = run_worker(
            srv.url, poll_s=0.05, max_batches=1, mode="remote",
            cache_dir=os.path.join(base, "wcache"), stop_event=stop,
        )
        assert served == 1
        assert service.queue.wait_idle(timeout=10)
        for a in accepted:
            job = service.queue.get(a["id"])
            assert job.status == "done", job.error
            # the signed result landed on the COORDINATOR's disk via
            # the upload path
            with open(svc_jobs.result_path(art, job.digest), "rb") as f:
                coord = f.read()
            local = svc_jobs.result_bytes(
                os.path.join(base, "wcache", "artifacts"), job.digest
            )
            assert coord == local  # byte-identical to the worker's copy
        code, _, doc = _request(srv.url + "/workers")
        [row] = doc["workers"].values()
        assert row["mode"] == "remote"
        assert row["transfers"]["uploads"] == 2
        assert row["transfers"]["downloads"] >= 2
        # the trace cache is digest-keyed
        assert os.path.isdir(
            os.path.join(base, "wcache", "traces", trace.digest)
        )
        # all leases released after completion
        assert svc_leases.scan_leases(art) == []
    finally:
        srv.stop()


@pytest.mark.slow
def test_fleet_wan_acceptance(tmp_path):
    """The full ISSUE 13 acceptance: remote-mode workers with isolated
    dirs under a flaky (drop/delay) HTTP shim, a mid-batch kill -9, the
    supervisor respawning, a forced crash-loop tripping the breaker —
    gate.fleet_wan_smoke IS the harness (also `make fleet-wan-smoke`)."""
    from tpusim.obs.gate import fleet_wan_smoke

    ok, msgs = fleet_wan_smoke(str(tmp_path))
    assert ok, "\n".join(msgs)
