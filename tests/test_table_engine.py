"""Incremental score-table engine (tpusim.sim.table_engine) must be
bit-identical to the sequential oracle engine (tpusim.sim.engine) — same
kernels, different evaluation schedule. Randomized create/delete mixes over
heterogeneous clusters pin the equivalence for every table-izable policy."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import random_cluster, random_pods
from tpusim.policies import make_policy
from tpusim.sim.engine import EV_CREATE, EV_DELETE, make_replay
from tpusim.sim.table_engine import build_pod_types, make_table_replay


def _events_with_deletes(num_pods, rng):
    """Creation for every pod; ~1/3 get a later deletion (stable order)."""
    kinds, idxs = [], []
    for i in range(num_pods):
        kinds.append(EV_CREATE)
        idxs.append(i)
        if rng.random() < 0.34 and i > 0:
            victim = int(rng.integers(0, i + 1))
            kinds.append(EV_DELETE)
            idxs.append(victim)
    # dedup double-deletes (unschedule of an already-deleted pod is a no-op
    # in both engines, but keep the trace clean)
    seen = set()
    ek, ei = [], []
    for k, i in zip(kinds, idxs):
        if k == EV_DELETE:
            if i in seen:
                continue
            seen.add(i)
        ek.append(k)
        ei.append(i)
    return jnp.asarray(ek, jnp.int32), jnp.asarray(ei, jnp.int32)


def _assert_equal(r0, r1):
    assert np.array_equal(np.asarray(r0.placed_node), np.asarray(r1.placed_node))
    assert np.array_equal(np.asarray(r0.dev_mask), np.asarray(r1.dev_mask))
    assert np.array_equal(np.asarray(r0.ever_failed), np.asarray(r1.ever_failed))
    for a, b in zip(jax.tree.leaves(r0.state), jax.tree.leaves(r1.state)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize(
    "policy,gpu_sel",
    [
        ("FGDScore", "FGDScore"),
        ("BestFitScore", "best"),
        ("GpuPackingScore", "worst"),
        ("GpuClusteringScore", "best"),
        ("DotProductScore", "DotProductScore"),
        ("PWRScore", "PWRScore"),
        ("Simon", "best"),
        # per-event-random configs: bit-identical since round 5 (the table
        # body follows the oracle's key-split discipline and recomputes
        # the draw per event)
        ("RandomScore", "best"),
        ("RandomScore", "random"),
        ("FGDScore", "random"),
    ],
    ids=lambda p: str(p),
)
def test_table_engine_matches_sequential(policy, gpu_sel):
    rng = np.random.default_rng(7)
    state, tp = random_cluster(rng, num_nodes=24)
    pods = random_pods(rng, num_pods=60)
    ev_kind, ev_pod = _events_with_deletes(60, rng)
    policies = [(make_policy(policy), 1000)]
    key = jax.random.PRNGKey(3)
    rank = jnp.asarray(rng.permutation(24).astype(np.int32))

    seq = make_replay(policies, gpu_sel=gpu_sel, report=False)
    r0 = seq(state, pods, ev_kind, ev_pod, tp, key, rank)
    tab = make_table_replay(policies, gpu_sel=gpu_sel)
    r1 = tab(state, pods, build_pod_types(pods), ev_kind, ev_pod, tp, key, rank)
    _assert_equal(r0, r1)


def test_table_engine_weighted_multi_policy():
    """Two weighted score plugins (the reference's PWR+FGD mixes,
    generate_run_scripts.py AllMethodList rows 08/11/12)."""
    rng = np.random.default_rng(11)
    state, tp = random_cluster(rng, num_nodes=16)
    pods = random_pods(rng, num_pods=40)
    ev_kind, ev_pod = _events_with_deletes(40, rng)
    policies = [(make_policy("PWRScore"), 500), (make_policy("FGDScore"), 500)]
    key = jax.random.PRNGKey(5)
    rank = jnp.asarray(rng.permutation(16).astype(np.int32))

    seq = make_replay(policies, gpu_sel="FGDScore", report=False)
    r0 = seq(state, pods, ev_kind, ev_pod, tp, key, rank)
    tab = make_table_replay(policies, gpu_sel="FGDScore")
    r1 = tab(state, pods, build_pod_types(pods), ev_kind, ev_pod, tp, key, rank)
    _assert_equal(r0, r1)


def test_table_engine_pinned_pods():
    """nodeSelector-pinned pods (snapshot re-bind path) stay a per-event
    feasibility mask, not part of the type key."""
    rng = np.random.default_rng(13)
    state, tp = random_cluster(rng, num_nodes=8)
    pods = random_pods(rng, num_pods=12)
    pinned = np.full(12, -1, np.int32)
    pinned[3] = 5
    pinned[7] = 2
    pods = pods._replace(pinned=jnp.asarray(pinned))
    ev_kind = jnp.zeros(12, jnp.int32)
    ev_pod = jnp.arange(12, dtype=jnp.int32)
    policies = [(make_policy("FGDScore"), 1000)]
    key = jax.random.PRNGKey(1)

    seq = make_replay(policies, gpu_sel="FGDScore", report=False)
    r0 = seq(state, pods, ev_kind, ev_pod, tp, key)
    tab = make_table_replay(policies, gpu_sel="FGDScore")
    r1 = tab(state, pods, build_pod_types(pods), ev_kind, ev_pod, tp, key)
    _assert_equal(r0, r1)
    placed = np.asarray(r1.placed_node)
    assert placed[3] in (5, -1) and placed[7] in (2, -1)


def test_random_policy_rejected_by_pallas_only():
    """Per-event randomness runs on the table engine since round 5; only
    the fused Pallas kernel (no jax.random inside) still rejects it."""
    from tpusim.sim.pallas_engine import make_pallas_replay

    make_table_replay([(make_policy("RandomScore"), 1000)])  # no raise
    with pytest.raises(ValueError):
        make_pallas_replay([(make_policy("RandomScore"), 1000)])
    with pytest.raises(ValueError):
        make_pallas_replay([(make_policy("FGDScore"), 1000)], gpu_sel="random")


def test_pod_type_partition():
    rng = np.random.default_rng(17)
    pods = random_pods(rng, num_pods=50)
    t = build_pod_types(pods)
    ks = int(t.share.cpu.shape[0])
    kw = int(t.whole.cpu.shape[0])
    # share group: exactly-one-GPU fractional requests
    assert bool(
        ((t.share.gpu_num == 1) & (t.share.gpu_milli > 0) & (t.share.gpu_milli < 1000)).all()
    )
    # ids must map each pod onto a type with identical resources
    tid = np.asarray(t.type_id)
    assert tid.min() >= 0 and tid.max() < ks + kw
    cat = lambda f: np.concatenate([np.asarray(getattr(t.share, f)), np.asarray(getattr(t.whole, f))])
    for f in ("cpu", "mem", "gpu_milli", "gpu_num", "gpu_mask"):
        assert np.array_equal(cat(f)[tid], np.asarray(getattr(pods, f)))


@pytest.mark.parametrize(
    "policy,gpu_sel",
    [
        ("FGDScore", "FGDScore"),
        ("BestFitScore", "best"),
        # tier-1 trim, ISSUE 16: per-event report rows are policy-agnostic
        # plumbing — two policies pin the contract; the rest ride
        # resume-smoke
        pytest.param("PWRScore", "PWRScore", marks=pytest.mark.slow),
        pytest.param("GpuPackingScore", "worst", marks=pytest.mark.slow),
    ],
    ids=lambda p: str(p),
)
def test_table_engine_report_rows_match_sequential(policy, gpu_sel):
    """Per-event report series: the table engine's telemetry through the
    shared post-pass must match the sequential oracle's in-scan rows
    (integer series exactly; float series to f32 tolerance — the post-pass
    accumulates row deltas where the oracle re-reduces per event)."""
    from tpusim.sim.metrics import compute_event_metrics

    rng = np.random.default_rng(23)
    state, tp = random_cluster(rng, num_nodes=12)
    pods = random_pods(rng, num_pods=30)
    ev_kind, ev_pod = _events_with_deletes(30, rng)
    policies = [(make_policy(policy), 1000)]
    key = jax.random.PRNGKey(9)
    rank = jnp.asarray(rng.permutation(12).astype(np.int32))

    seq = make_replay(policies, gpu_sel=gpu_sel, report=True)
    r0 = seq(state, pods, ev_kind, ev_pod, tp, key, rank)
    tab = make_table_replay(policies, gpu_sel=gpu_sel)
    r1 = tab(state, pods, build_pod_types(pods), ev_kind, ev_pod, tp, key, rank)
    _assert_equal(r0, r1)
    m1 = compute_event_metrics(
        state, pods, ev_kind, ev_pod, r1.event_node, r1.event_dev, tp
    )
    for f in ("used_nodes", "used_gpus", "used_gpu_milli", "used_cpu_milli",
              "arrived_gpu_milli", "arrived_cpu_milli"):
        np.testing.assert_array_equal(
            np.asarray(getattr(m1, f)), np.asarray(getattr(r0.metrics, f)),
            err_msg=f,
        )
    for f in ("frag_amounts", "power_cpu", "power_gpu"):
        np.testing.assert_allclose(
            np.asarray(getattr(m1, f)), np.asarray(getattr(r0.metrics, f)),
            rtol=2e-5, atol=1e-2, err_msg=f,
        )


def test_bucketed_padding_equivalence():
    """run_events' shape bucketing (inert pods + EV_SKIP events + dummy
    types) must not change results."""
    from tpusim.io.trace import NodeRow, PodRow, pods_to_specs
    from tpusim.sim.driver import Simulator, SimulatorConfig

    rng = np.random.default_rng(31)
    nodes = [
        NodeRow(f"n{i}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], 10))
    ]
    pods = [
        PodRow(f"p{i}", int(rng.choice([1000, 4000])), 1024,
               int(rng.choice([0, 1])), 500)
        for i in range(23)
    ]
    sim = Simulator(nodes, SimulatorConfig(
        policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
        report_per_event=True,
    ))
    sim.set_workload_pods(pods)
    sim.set_typical_pods()
    specs = pods_to_specs(pods)
    ev_kind = jnp.zeros(23, jnp.int32)
    ev_pod = jnp.arange(23, dtype=jnp.int32)
    key = jax.random.PRNGKey(2)
    r0 = sim.run_events(sim.init_state, specs, ev_kind, ev_pod, key, bucket=1)
    r1 = sim.run_events(sim.init_state, specs, ev_kind, ev_pod, key, bucket=512)
    _assert_equal(r0, r1)
    for a, b in zip(r0.metrics, r1.metrics):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


@pytest.mark.slow  # tier-1 trim, ISSUE 16: the unswitched-select A/B knob's big compile; rides resume-smoke
def test_unswitched_flat_bit_identity():
    """Round 18 A/B pin: the flat body's unconditional-select layout
    (`unswitched=True` — the shard engine's Round-15 form ported back)
    is bit-identical to the default event-switch layout across
    create/delete mixes, a policy mix with normalization, and
    per-event randomness (RandomScore recomputes its draw from the same
    pre-split k_rand either way)."""
    rng = np.random.default_rng(23)
    state, tp = random_cluster(rng, num_nodes=20)
    pods = random_pods(rng, num_pods=50)
    ev_kind, ev_pod = _events_with_deletes(50, rng)
    key = jax.random.PRNGKey(5)
    rank = jnp.asarray(rng.permutation(20).astype(np.int32))
    types = build_pod_types(pods)
    for policies, gpu_sel in (
        ([("FGDScore", 1000)], "FGDScore"),
        ([("PWRScore", 500), ("BestFitScore", 500)], "best"),
        ([("RandomScore", 1000)], "random"),
    ):
        pol = [(make_policy(n), w) for n, w in policies]
        switched = make_table_replay(pol, gpu_sel=gpu_sel, block_size=-1)
        unswitched = make_table_replay(
            pol, gpu_sel=gpu_sel, block_size=-1, unswitched=True
        )
        r0 = switched(state, pods, types, ev_kind, ev_pod, tp, key, rank)
        r1 = unswitched(state, pods, types, ev_kind, ev_pod, tp, key, rank)
        _assert_equal(r0, r1)

    # the user-reachable compositions exercise the unswitched merge code
    # the plain path does not: the decision-pytree where-merge, and the
    # fault build's kc clipping (fault kinds must fall through to skip
    # in both layouts)
    pol = [(make_policy("FGDScore"), 1000)]
    for kw in (dict(decisions=True),):
        r0 = make_table_replay(pol, gpu_sel="FGDScore", block_size=-1, **kw)(
            state, pods, types, ev_kind, ev_pod, tp, key, rank
        )
        r1 = make_table_replay(
            pol, gpu_sel="FGDScore", block_size=-1, unswitched=True, **kw
        )(state, pods, types, ev_kind, ev_pod, tp, key, rank)
        _assert_equal(r0, r1)
        for a, b in zip(jax.tree.leaves(r0.decisions),
                        jax.tree.leaves(r1.decisions)):
            assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # tier-1 trim, ISSUE 16: same knob through the fault lane; rides resume-smoke
def test_unswitched_fault_lane_bit_identity():
    """The unswitched layout under the in-scan fault plane: the driver's
    run_with_faults scan lane threads SimulatorConfig.unswitched_select,
    so the full fault trajectory (placements, DisruptionMetrics) must be
    bit-identical to the default switch layout."""
    from tpusim.io.trace import NodeRow, PodRow
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.faults import FaultConfig

    rng = np.random.default_rng(13)
    nodes = [
        NodeRow(f"n{i}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([2, 4, 8], 10))
    ]
    pods = [
        PodRow(f"p{i}", int(rng.choice([1000, 4000])), 1024, 1,
               int(rng.choice([300, 500, 1000])))
        for i in range(40)
    ]
    fcfg = FaultConfig(mtbf_events=12, mttr_events=10,
                       evict_every_events=9, seed=3)
    results = []
    for unswitched in (False, True):
        sim = Simulator(nodes, SimulatorConfig(
            policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
            engine="table", block_size=-1, seed=7,
            report_per_event=False, fault_mode="scan",
            unswitched_select=unswitched,
        ))
        sim.set_workload_pods(list(pods))
        results.append(sim.run_with_faults(fcfg))
    r0, r1 = results
    assert sim._last_engine == "table (fault lane)"
    np.testing.assert_array_equal(
        np.asarray(r0.placed_node), np.asarray(r1.placed_node)
    )
    np.testing.assert_array_equal(
        np.asarray(r0.dev_mask), np.asarray(r1.dev_mask)
    )
