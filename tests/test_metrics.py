"""The vectorized metrics post-pass (tpusim.sim.metrics) must reproduce the
sequential oracle's in-scan per-event report rows: integer series exactly,
float series to f32 tolerance (the post-pass accumulates cumulative row
deltas where the oracle re-reduces the cluster each event — same kernels,
different summation order). Engine-cross identity (table/pallas/batched all
byte-identical) follows from the telemetry equality the engine tests pin."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import random_cluster, random_pods
from tests.test_table_engine import _events_with_deletes
from tpusim.policies import make_policy
from tpusim.sim.engine import EV_SKIP, make_replay
from tpusim.sim.metrics import compute_event_metrics

INT_FIELDS = (
    "used_nodes", "used_gpus", "used_gpu_milli", "used_cpu_milli",
    "arrived_gpu_milli", "arrived_cpu_milli",
)
FLOAT_FIELDS = ("frag_amounts", "power_cpu", "power_gpu")


@pytest.mark.parametrize(
    "policy,gpu_sel",
    [
        ("FGDScore", "FGDScore"),
        ("BestFitScore", "best"),
        ("PWRScore", "PWRScore"),
        ("RandomScore", "random"),
    ],
    ids=lambda p: str(p),
)
def test_postpass_matches_sequential_inscan(policy, gpu_sel):
    rng = np.random.default_rng(7)
    state, tp = random_cluster(rng, num_nodes=14)
    pods = random_pods(rng, num_pods=40)
    ev_kind, ev_pod = _events_with_deletes(40, rng)
    # inject a skip event and an unfittable pod (failed create) to exercise
    # the telemetry's -1 rows
    ev_kind = jnp.concatenate([ev_kind, jnp.asarray([EV_SKIP], jnp.int32)])
    ev_pod = jnp.concatenate([ev_pod, jnp.asarray([0], jnp.int32)])
    policies = [(make_policy(policy), 1000)]
    key = jax.random.PRNGKey(3)
    rank = jnp.asarray(rng.permutation(14).astype(np.int32))

    seq = make_replay(policies, gpu_sel=gpu_sel, report=True)
    oracle = seq(state, pods, ev_kind, ev_pod, tp, key, rank)
    post = compute_event_metrics(
        state, pods, ev_kind, ev_pod, oracle.event_node, oracle.event_dev, tp
    )
    for f in INT_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(post, f)), np.asarray(getattr(oracle.metrics, f)),
            err_msg=f,
        )
    for f in FLOAT_FIELDS:
        np.testing.assert_allclose(
            np.asarray(getattr(post, f)),
            np.asarray(getattr(oracle.metrics, f)),
            rtol=2e-5, atol=1e-2, err_msg=f,
        )


def test_postpass_padding_invariance():
    """EV_SKIP padding rows (the bucketing contract) must not perturb the
    series of the true prefix."""
    rng = np.random.default_rng(11)
    state, tp = random_cluster(rng, num_nodes=10)
    pods = random_pods(rng, num_pods=20)
    ev_kind, ev_pod = _events_with_deletes(20, rng)
    policies = [(make_policy("FGDScore"), 1000)]
    key = jax.random.PRNGKey(5)
    seq = make_replay(policies, gpu_sel="FGDScore", report=False)
    out = seq(state, pods, ev_kind, ev_pod, tp, key, None)

    e = int(ev_kind.shape[0])
    pad = 17
    ev_kind_p = jnp.concatenate([ev_kind, jnp.full(pad, EV_SKIP, jnp.int32)])
    ev_pod_p = jnp.concatenate([ev_pod, jnp.zeros(pad, jnp.int32)])
    en_p = jnp.concatenate([out.event_node, jnp.full(pad, -1, jnp.int32)])
    ed_p = jnp.concatenate(
        [out.event_dev, jnp.zeros((pad, 8), out.event_dev.dtype)]
    )
    m0 = compute_event_metrics(
        state, pods, ev_kind, ev_pod, out.event_node, out.event_dev, tp
    )
    m1 = compute_event_metrics(state, pods, ev_kind_p, ev_pod_p, en_p, ed_p, tp)
    for f in INT_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(m1, f))[:e], np.asarray(getattr(m0, f)),
            err_msg=f,
        )
    for f in FLOAT_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(m1, f))[:e], np.asarray(getattr(m0, f)),
            err_msg=f,
        )
