"""The interactive what-if serving plane (tpusim.svc; ISSUE 16).

Pins the serving-side fork contracts around tests/test_fork.py's
driver-level bit-identity:

  1. the job vocabulary: {"base": true} and {"fork": {...}} specs,
     their validation errors, and the family keys that put a fork and
     its from-event-0 "full" twin on ONE wave while keeping base and
     plain jobs apart;
  2. the fork index: signed base entries round-trip, torn/foreign
     entries read as missing (and are deleted), nearest-checkpoint
     walk-back is a pure directory listing;
  3. the latency plane: claim_family's targeted non-blocking claim,
     per-kind admission->result percentiles, the ForkWave's
     tail-relative progress publishing;
  4. (slow, `make resume-smoke`) the POST path end-to-end: premature
     forks 400, a base run leaves a discoverable ladder, a warm fork's
     result doc is field-identical to its full twin while executing
     only the divergent tail, weight-changing forks 400 loudly, and a
     second wave of forks adds ZERO compiled wave executables.
"""

import json
import os
import time
from types import SimpleNamespace

import numpy as np
import pytest

from tests.test_svc import _mk_cluster, _mk_pods
from tpusim.svc import forks as svc_forks
from tpusim.svc import jobs as svc_jobs
from tpusim.svc.api import JobService
from tpusim.svc.batcher import JobQueue
from tpusim.svc.worker import TraceRef, Worker

FAM = [["FGDScore", 1000], ["BestFitScore", 500]]


# ---------------------------------------------------------------------------
# 1. vocabulary + family keys (no device)
# ---------------------------------------------------------------------------


def test_fork_spec_vocabulary():
    spec = svc_jobs.validate_job({"base": True})
    assert spec.base is True and spec.fork == ()

    fork = {"base": "a" * 64, "event": 5, "tail": [[0, 1], [1, 2]]}
    spec = svc_jobs.validate_job({"fork": dict(fork)})
    assert spec.fork == ("a" * 64, 5, "fork", ((0, 1), (1, 2)))
    spec = svc_jobs.validate_job({"fork": dict(fork, mode="full")})
    assert spec.fork[2] == "full"

    for bad in (
        {"base": "zz"},  # not a run digest
        {"base": "a" * 64, "event": -1, "tail": []},
        {"base": "a" * 64, "event": 1, "tail": [[7, 0]]},  # bad kind
        {"base": "a" * 64, "event": 1, "tail": [], "mode": "warm"},
    ):
        with pytest.raises(ValueError):
            svc_jobs.validate_job({"fork": bad})
    with pytest.raises(ValueError, match="base excludes fork"):
        svc_jobs.validate_job({"base": True, "fork": dict(fork)})
    with pytest.raises(ValueError, match="exclude fault"):
        svc_jobs.validate_job(
            {"base": True, "fault": {"mtbf_events": 10}}
        )
    with pytest.raises(ValueError, match="chunked carry"):
        svc_jobs.validate_job({"base": True, "engine": "sequential"})


def test_fork_family_keys():
    """A fork and its full twin share one family (one wave, one set of
    compiled entries); forks of DIFFERENT bases don't; base and plain
    jobs batch apart from both."""
    fork = {"base": "a" * 64, "event": 5, "tail": [[1, 0]]}
    f = svc_jobs.validate_job({"fork": dict(fork)})
    v = svc_jobs.validate_job({"fork": dict(fork, mode="full")})
    other = svc_jobs.validate_job({"fork": dict(fork, base="b" * 64)})
    base = svc_jobs.validate_job({"base": True})
    plain = svc_jobs.validate_job({})
    assert f.family_key() == v.family_key()
    assert f.family_key() != other.family_key()
    assert len({f.family_key(), base.family_key(),
                plain.family_key()}) == 3


# ---------------------------------------------------------------------------
# 2. the fork index
# ---------------------------------------------------------------------------


def test_base_entry_roundtrip(tmp_path):
    d = str(tmp_path)
    digest, run = "c" * 64, "d" * 64
    payload = {"policies": [["FGDScore", 1000]], "weights": [1000]}
    path = svc_forks.write_base_entry(d, digest, run, 3, 40, 24, payload)
    doc = svc_forks.load_base_entry(d, digest)
    assert doc["run_digest"] == run and doc["checkpoint_every"] == 3
    assert doc["events"] == 40 and doc["spec"] == payload
    assert svc_forks.load_base_entry(d, "e" * 64) is None

    # a torn entry reads as missing AND is deleted (never served)
    with open(path, "r+b") as fh:
        fh.truncate(os.path.getsize(path) // 2)
    assert svc_forks.load_base_entry(d, digest) is None
    assert not os.path.isfile(path)


def test_nearest_checkpoint_is_a_listing(tmp_path):
    from tpusim.io.storage import save_checkpoint

    d, run = str(tmp_path), "f" * 64
    for cur in (4, 8, 12):
        save_checkpoint(d, run, cur, {"x": np.zeros(2)})
    near = svc_forks.nearest_checkpoint
    assert near(d, run, 12) == 12
    assert near(d, run, 11) == 8  # walk back, never forward
    assert near(d, run, 3) is None
    assert near(d, "0" * 64, 12) is None


# ---------------------------------------------------------------------------
# 3. the latency plane, host side
# ---------------------------------------------------------------------------


def test_claim_family_targeted_nonblocking():
    queue = JobQueue(maxsize=16, lane_width=4)
    fork = {"base": "a" * 64, "event": 5, "tail": [[1, 0]]}
    fspec = svc_jobs.validate_job({"fork": dict(fork)})
    pspec = svc_jobs.validate_job({})
    jobs = [
        queue.submit(fspec, f"{i:064x}") for i in range(3)
    ] + [queue.submit(pspec, f"{99:064x}")]
    got = queue.claim_family("w1", fspec.family_key(), max_n=2)
    assert [j.id for j in got] == [jobs[0].id, jobs[1].id]  # FIFO
    assert all(j.worker == "w1" and j.claimed_unix > 0 for j in got)
    assert queue.claim_family("w1", fspec.family_key(), max_n=0) == []
    # the plain job is NOT claimable through the fork family
    rest = queue.claim_family("w1", fspec.family_key(), max_n=8)
    assert [j.id for j in rest] == [jobs[2].id]
    assert queue.depth() == 1


def test_latency_percentiles_by_kind():
    queue = JobQueue(maxsize=16, lane_width=4)
    fork = {"base": "a" * 64, "event": 5, "tail": [[1, 0]]}
    kinds = {
        "base": {"base": True}, "fork": {"fork": dict(fork)},
        "full": {"fork": dict(fork, mode="full")}, "plain": {},
    }
    for i, (kind, doc) in enumerate(kinds.items()):
        spec = svc_jobs.validate_job(doc)
        job = queue.submit(spec, f"{i:064x}")
        assert job.kind() == kind
        queue.mark_done(job, {"ok": True})
        d = job.describe()
        assert d["latency_s"] >= 0 and d["digest"] == job.digest
    lat = queue.latency_percentiles()
    assert sorted(lat) == ["base", "fork", "full", "plain"]
    for v in lat.values():
        assert v["count"] == 1 and v["p99_s"] >= v["p50_s"] >= 0


def test_forkwave_tail_relative_progress():
    """The honest-progress satellite at the wave layer: a restored
    lane's published done/total/rate cover ITS divergent tail — the
    base prefix the checkpoint skipped never inflates them."""
    from tpusim.svc.waves import ForkWave

    seen = []
    monitor = SimpleNamespace(
        publish_job_progress=lambda jid, info: seen.append((jid, info))
    )
    fw = ForkWave(wave=None, monitor=monitor)
    lane = {
        "job": SimpleNamespace(id="j1"), "cursor": 30, "real": 33,
        "c0": 27, "joined": time.time() - 1.0, "degrade": False,
        "mode": "fork",
    }
    fw._publish(lane)
    jid, info = seen[-1]
    assert jid == "j1"
    assert info["done"] == 3 and info["total"] == 6  # tail-relative
    assert 0 < info["ev_per_s"] < 10  # ~3 events over ~1s, never ~30
    assert info["source_cursor"] == 27 and info["mode"] == "fork"


# ---------------------------------------------------------------------------
# 4. the POST path end-to-end (slow; `make resume-smoke`)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fork_serving_end_to_end(tmp_path):
    rng = np.random.default_rng(3)
    nodes, pods = _mk_cluster(rng), _mk_pods(rng)
    trace = TraceRef(
        "default", nodes, pods, svc_jobs.trace_digest(nodes, pods)
    )
    queue = JobQueue(maxsize=16, lane_width=4)
    worker = Worker(queue, {"default": trace}, str(tmp_path),
                    lease_files=False)
    service = JobService(queue, worker, {"default": trace},
                         str(tmp_path))

    def post(doc):
        code, _, body = service.handle(
            "POST", "/jobs", json.dumps(doc).encode()
        )[:3]
        return code, json.loads(body.decode())

    def drain():
        while True:
            batch = queue.next_batch(timeout=0)
            if not batch:
                return
            worker.run_batch(batch)

    # a fork of a base nobody ran answers 400, not a silent cold run
    code, body = post({"fork": {"base": "0" * 64, "event": 5,
                                "tail": []}})
    assert code == 400 and "no finished base run" in body["error"]

    code, body = post({"policies": FAM, "weights": [1000, 500],
                       "seed": 7, "base": True})
    assert code == 202
    base_digest = body["digest"]
    drain()
    bjob = queue.get(body["id"])
    assert bjob.status == "done", (bjob.status, bjob.error)
    br = bjob.result["base_run"]
    E, every = br["events"], br["checkpoint_every"]
    assert E > 0 and every > 0
    # the run left a discoverable ladder + index entry behind
    assert svc_forks.load_base_entry(
        str(tmp_path), base_digest
    )["run_digest"] == br["run_digest"]
    from tpusim.io.storage import iter_checkpoints

    ck = svc_forks.checkpoint_dir(str(tmp_path))
    assert len(iter_checkpoints(ck, br["run_digest"])) >= E // every - 1

    # warm fork vs its from-event-0 twin: one wave, identical docs
    F = (E * 3) // 4
    tail = [[1, 3], [1, 5], [0, 3]]
    code, fb = post({"fork": {"base": base_digest, "event": F,
                              "tail": tail}})
    assert code == 202
    code, vb = post({"fork": {"base": base_digest, "event": F,
                              "tail": tail, "mode": "full"}})
    assert code == 202 and fb["digest"] != vb["digest"]
    drain()
    fj, vj = queue.get(fb["id"]), queue.get(vb["id"])
    assert fj.status == "done", (fj.status, fj.error)
    assert vj.status == "done", (vj.status, vj.error)
    for k in ("placements_sha256", "counters", "gpu_alloc_pct",
              "frag_gpu_milli", "placed_node", "placed", "failed"):
        assert fj.result[k] == vj.result[k], k
    fm, vm = fj.result["fork"], vj.result["fork"]
    assert fm["mode"] == "fork" and fm["degrade"] is False
    assert fm["source_cursor"] > 0
    assert fm["events_executed"] <= len(tail) + every  # the warm win
    assert vm["source_cursor"] == 0
    assert vm["events_executed"] == F + len(tail)

    # weights are baked into the restored carry: changing them must be
    # a loud submit-time rejection, never a silently-cold fork
    code, body = post({"fork": {"base": base_digest, "event": F,
                                "tail": tail}, "weights": [999, 500]})
    assert code == 400 and "weight" in body["error"]

    # a second wave at different divergence points reuses every
    # compiled wave entry (step/scatter/finish) — zero recompiles
    x0 = worker.wave_executables()
    for i in (1, 2, 3):
        code, _ = post({"fork": {"base": base_digest,
                                 "event": F - i * every, "tail": tail}})
        assert code == 202
    drain()
    assert worker.wave_executables() == x0
    stats = worker.wave_stats()
    assert stats["waves_run"] >= 2 and stats["degrades"] == 0
    lat = queue.latency_percentiles()
    assert {"base", "fork", "full"} <= set(lat)
