"""Native C++ Bellman evaluator must match the Python implementation
exactly (same recursion, cutoffs, memo semantics) on randomized states."""

import numpy as np
import pytest

from tests.fixtures import typical_rows_gpu_host
from tpusim.native import BellmanEvaluator
from tpusim.ops.frag import node_frag_bellman


def test_native_available():
    ev = BellmanEvaluator(typical_rows_gpu_host())
    assert ev.native, "native toolchain present in this image; must compile"


def test_native_matches_python():
    t = typical_rows_gpu_host()
    ev = BellmanEvaluator(t)
    rng = np.random.default_rng(9)
    pymemo = {}
    for _ in range(40):
        g = tuple(int(x) for x in rng.choice([0, 100, 250, 465, 500, 750, 1000], 8))
        cpu = int(rng.choice([1000, 4000, 16000, 64000]))
        ty = int(rng.integers(-1, 4))
        a = ev.eval(cpu, g, ty)
        b = node_frag_bellman((cpu, g, ty), t, memo=pymemo)
        assert a == pytest.approx(b, rel=1e-12, abs=1e-9), (cpu, g, ty)
    assert ev.memo_size() > 0


def test_native_degenerate_pods():
    """zero-milli multi-GPU pod and masked pods."""
    t = [(4000, 0, 2, 0, 0.5), (8000, 500, 1, 1 << 2, 0.5)]
    ev = BellmanEvaluator(t)
    for node in [(16000, (1000, 1000, 500, 0, 0, 0, 0, 0), 2),
                 (16000, (1000, 1000, 500, 0, 0, 0, 0, 0), 1),
                 (100, (0,) * 8, -1)]:
        assert ev.eval(*node) == pytest.approx(
            node_frag_bellman(node, t), abs=1e-9
        )


def test_eval_series_matches_per_event_loop():
    """bellman_series (one native call over the event stream) must equal the
    per-event eval() bookkeeping it replaced (driver._bellman_series's old
    loop): same touched-node updates, same memo evolution."""
    t = typical_rows_gpu_host()
    rng = np.random.default_rng(3)
    n, e = 12, 60
    cpu_left = rng.choice([16000, 32000, 64000], n).astype(np.int32)
    gpu_left = rng.choice([0, 250, 500, 1000], (n, 8)).astype(np.int32)
    gpu_type = rng.integers(-1, 4, n).astype(np.int32)
    ev_node = rng.integers(-1, n, e).astype(np.int32)
    ev_dev = np.zeros((e, 8), bool)
    for k in range(e):
        ev_dev[k, rng.integers(0, 8)] = True
    ev_sign = rng.choice([1, -1], e).astype(np.int8)
    ev_cpu = rng.choice([0, 1000, 4000], e).astype(np.int32)
    ev_gpu = rng.choice([0, 100, 250], e).astype(np.int32)

    native = BellmanEvaluator(t)
    got = native.eval_series(
        cpu_left, gpu_left, gpu_type, ev_node, ev_dev, ev_sign, ev_cpu, ev_gpu
    )

    # reference loop through eval() on a fresh evaluator (fresh memo)
    ref_ev = BellmanEvaluator(t)
    cpu, gpu = cpu_left.copy(), gpu_left.copy()
    val = np.array(
        [ref_ev.eval(int(cpu[i]), gpu[i], int(gpu_type[i])) for i in range(n)]
    )
    total = float(val.sum())
    want = np.empty(e)
    for k in range(e):
        node = int(ev_node[k])
        if node >= 0:
            cpu[node] -= int(ev_sign[k]) * ev_cpu[k]
            gpu[node][ev_dev[k]] -= int(ev_sign[k]) * ev_gpu[k]
            total -= float(val[node])
            val[node] = ref_ev.eval(int(cpu[node]), gpu[node], int(gpu_type[node]))
            total += float(val[node])
        want[k] = total
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-9)


def test_memo_reuse_matches_python_order_dependence():
    """Memo-carrying evaluations must match a Python memo evolved in the
    same order (memoized values embed first-visit cum_prob context)."""
    t = typical_rows_gpu_host()
    ev = BellmanEvaluator(t)
    pymemo = {}
    seq = [
        (64000, (1000,) * 8, 1),
        (60000, (1000,) * 7 + (535,), 1),
        (64000, (1000,) * 8, 1),
        (32000, (1000, 1000, 465, 0, 0, 0, 0, 0), 1),
    ]
    for node in seq:
        assert ev.eval(*node) == pytest.approx(
            node_frag_bellman(node, t, memo=pymemo), abs=1e-9
        )


def test_truncation_counter_fires_on_pathological_distribution():
    """A distribution that recurses arbitrarily deep (zero-CPU pod at
    frequency ~1 nibbling 1 milli per step keeps cum_prob high while the
    state changes) must trip the defensive max_depth cutoff — and the
    counter must expose it, in both the native and Python paths."""
    t = [(0, 1, 1, 0, 0.999), (1000, 1000, 1, 0, 0.001)]
    node = (64000, (1000,) * 8, 1)

    ev = BellmanEvaluator(t, max_depth=16)
    ev.eval(*node)
    assert ev.truncations() > 0
    assert ev.max_depth_seen() >= 16

    stats = {}
    node_frag_bellman(node, t, max_depth=16, stats=stats)
    assert stats["truncations"] > 0
    assert stats["max_depth_seen"] >= 16

    # native and python agree on the truncated value too
    ev2 = BellmanEvaluator(t, max_depth=16)
    assert ev2.eval(*node) == pytest.approx(
        node_frag_bellman(node, t, max_depth=16), abs=1e-9
    )

    # with enough headroom the same fixture converges without truncating
    # (cum_prob decays below 1/total eventually) and yields a different value
    deep = BellmanEvaluator(t, max_depth=100_000)
    v_deep = deep.eval(*node)
    assert deep.truncations() == 0
    assert v_deep != pytest.approx(ev.eval(*node), abs=1e-6)


@pytest.mark.slow  # a full openb replay through the Bellman series
def test_truncation_never_fires_on_full_openb_replay():
    """The max_depth=64 bound (absent from the Go reference,
    frag.go:231-283) must be pure headroom on the real workload: replay the
    full openb default trace (FGD, tune 1.3 — the flagship experiment) and
    assert zero truncations across the whole per-event bellman series."""
    import os

    import jax
    import jax.numpy as jnp

    from tpusim.io.trace import (
        build_events,
        load_node_csv,
        load_pod_csv,
        pods_to_specs,
    )
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.engine import EV_CREATE
    from tpusim.sim.typical import TypicalPodsConfig

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    node_csv = os.path.join(repo, "data/csv/openb_node_list_gpu_node.csv")
    pod_csv = os.path.join(repo, "data/csv/openb_pod_list_default.csv")
    if not (os.path.isfile(node_csv) and os.path.isfile(pod_csv)):
        pytest.skip("openb trace not present")

    cfg = SimulatorConfig(
        policies=(("FGDScore", 1000),),
        gpu_sel_method="FGDScore",
        tuning_ratio=1.3,
        tuning_seed=42,
        seed=42,
        shuffle_pod=True,
        report_per_event=False,
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
    )
    sim = Simulator(load_node_csv(node_csv), cfg)
    sim.set_workload_pods(load_pod_csv(pod_csv))
    sim.set_typical_pods()
    pods = sim.prepare_pods()
    specs = pods_to_specs(pods, sim.node_index)
    ev_kind, ev_pod = build_events(pods)
    out = sim.run_events(
        sim.init_state, specs, jnp.asarray(ev_kind), jnp.asarray(ev_pod),
        jax.random.PRNGKey(42), bucket=1,
    )

    ev = BellmanEvaluator(sim._typical_host_rows())
    state = jax.tree.map(np.asarray, sim.init_state)
    pod_cpu = np.fromiter((p.cpu_milli for p in pods), np.int32, len(pods))
    pod_gpu = np.fromiter((p.gpu_milli for p in pods), np.int32, len(pods))
    ev_pods = np.asarray(ev_pod)
    series = ev.eval_series(
        state.cpu_left, state.gpu_left, state.gpu_type,
        np.asarray(out.event_node), np.asarray(out.event_dev),
        np.where(np.asarray(ev_kind) == EV_CREATE, 1, -1).astype(np.int8),
        pod_cpu[ev_pods], pod_gpu[ev_pods],
    )
    assert len(series) == len(ev_pods)
    assert ev.truncations() == 0, (
        f"max_depth=64 truncated {ev.truncations()} times on openb"
    )
    # observed headroom: the openb distribution's cum_prob cutoff bounds
    # recursion far below the 64 guard
    assert 0 < ev.max_depth_seen() < 64
