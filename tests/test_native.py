"""Native C++ Bellman evaluator must match the Python implementation
exactly (same recursion, cutoffs, memo semantics) on randomized states."""

import numpy as np
import pytest

from tests.fixtures import typical_rows_gpu_host
from tpusim.native import BellmanEvaluator
from tpusim.ops.frag import node_frag_bellman


def test_native_available():
    ev = BellmanEvaluator(typical_rows_gpu_host())
    assert ev.native, "native toolchain present in this image; must compile"


def test_native_matches_python():
    t = typical_rows_gpu_host()
    ev = BellmanEvaluator(t)
    rng = np.random.default_rng(9)
    pymemo = {}
    for _ in range(40):
        g = tuple(int(x) for x in rng.choice([0, 100, 250, 465, 500, 750, 1000], 8))
        cpu = int(rng.choice([1000, 4000, 16000, 64000]))
        ty = int(rng.integers(-1, 4))
        a = ev.eval(cpu, g, ty)
        b = node_frag_bellman((cpu, g, ty), t, memo=pymemo)
        assert a == pytest.approx(b, rel=1e-12, abs=1e-9), (cpu, g, ty)
    assert ev.memo_size() > 0


def test_native_degenerate_pods():
    """zero-milli multi-GPU pod and masked pods."""
    t = [(4000, 0, 2, 0, 0.5), (8000, 500, 1, 1 << 2, 0.5)]
    ev = BellmanEvaluator(t)
    for node in [(16000, (1000, 1000, 500, 0, 0, 0, 0, 0), 2),
                 (16000, (1000, 1000, 500, 0, 0, 0, 0, 0), 1),
                 (100, (0,) * 8, -1)]:
        assert ev.eval(*node) == pytest.approx(
            node_frag_bellman(node, t), abs=1e-9
        )


def test_memo_reuse_matches_python_order_dependence():
    """Memo-carrying evaluations must match a Python memo evolved in the
    same order (memoized values embed first-visit cum_prob context)."""
    t = typical_rows_gpu_host()
    ev = BellmanEvaluator(t)
    pymemo = {}
    seq = [
        (64000, (1000,) * 8, 1),
        (60000, (1000,) * 7 + (535,), 1),
        (64000, (1000,) * 8, 1),
        (32000, (1000, 1000, 465, 0, 0, 0, 0, 0), 1),
    ]
    for node in seq:
        assert ev.eval(*node) == pytest.approx(
            node_frag_bellman(node, t, memo=pymemo), abs=1e-9
        )
