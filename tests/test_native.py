"""Native C++ Bellman evaluator must match the Python implementation
exactly (same recursion, cutoffs, memo semantics) on randomized states."""

import numpy as np
import pytest

from tests.fixtures import typical_rows_gpu_host
from tpusim.native import BellmanEvaluator
from tpusim.ops.frag import node_frag_bellman


def test_native_available():
    ev = BellmanEvaluator(typical_rows_gpu_host())
    assert ev.native, "native toolchain present in this image; must compile"


def test_native_matches_python():
    t = typical_rows_gpu_host()
    ev = BellmanEvaluator(t)
    rng = np.random.default_rng(9)
    pymemo = {}
    for _ in range(40):
        g = tuple(int(x) for x in rng.choice([0, 100, 250, 465, 500, 750, 1000], 8))
        cpu = int(rng.choice([1000, 4000, 16000, 64000]))
        ty = int(rng.integers(-1, 4))
        a = ev.eval(cpu, g, ty)
        b = node_frag_bellman((cpu, g, ty), t, memo=pymemo)
        assert a == pytest.approx(b, rel=1e-12, abs=1e-9), (cpu, g, ty)
    assert ev.memo_size() > 0


def test_native_degenerate_pods():
    """zero-milli multi-GPU pod and masked pods."""
    t = [(4000, 0, 2, 0, 0.5), (8000, 500, 1, 1 << 2, 0.5)]
    ev = BellmanEvaluator(t)
    for node in [(16000, (1000, 1000, 500, 0, 0, 0, 0, 0), 2),
                 (16000, (1000, 1000, 500, 0, 0, 0, 0, 0), 1),
                 (100, (0,) * 8, -1)]:
        assert ev.eval(*node) == pytest.approx(
            node_frag_bellman(node, t), abs=1e-9
        )


def test_eval_series_matches_per_event_loop():
    """bellman_series (one native call over the event stream) must equal the
    per-event eval() bookkeeping it replaced (driver._bellman_series's old
    loop): same touched-node updates, same memo evolution."""
    t = typical_rows_gpu_host()
    rng = np.random.default_rng(3)
    n, e = 12, 60
    cpu_left = rng.choice([16000, 32000, 64000], n).astype(np.int32)
    gpu_left = rng.choice([0, 250, 500, 1000], (n, 8)).astype(np.int32)
    gpu_type = rng.integers(-1, 4, n).astype(np.int32)
    ev_node = rng.integers(-1, n, e).astype(np.int32)
    ev_dev = np.zeros((e, 8), bool)
    for k in range(e):
        ev_dev[k, rng.integers(0, 8)] = True
    ev_sign = rng.choice([1, -1], e).astype(np.int8)
    ev_cpu = rng.choice([0, 1000, 4000], e).astype(np.int32)
    ev_gpu = rng.choice([0, 100, 250], e).astype(np.int32)

    native = BellmanEvaluator(t)
    got = native.eval_series(
        cpu_left, gpu_left, gpu_type, ev_node, ev_dev, ev_sign, ev_cpu, ev_gpu
    )

    # reference loop through eval() on a fresh evaluator (fresh memo)
    ref_ev = BellmanEvaluator(t)
    cpu, gpu = cpu_left.copy(), gpu_left.copy()
    val = np.array(
        [ref_ev.eval(int(cpu[i]), gpu[i], int(gpu_type[i])) for i in range(n)]
    )
    total = float(val.sum())
    want = np.empty(e)
    for k in range(e):
        node = int(ev_node[k])
        if node >= 0:
            cpu[node] -= int(ev_sign[k]) * ev_cpu[k]
            gpu[node][ev_dev[k]] -= int(ev_sign[k]) * ev_gpu[k]
            total -= float(val[node])
            val[node] = ref_ev.eval(int(cpu[node]), gpu[node], int(gpu_type[node]))
            total += float(val[node])
        want[k] = total
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-9)


def test_memo_reuse_matches_python_order_dependence():
    """Memo-carrying evaluations must match a Python memo evolved in the
    same order (memoized values embed first-visit cum_prob context)."""
    t = typical_rows_gpu_host()
    ev = BellmanEvaluator(t)
    pymemo = {}
    seq = [
        (64000, (1000,) * 8, 1),
        (60000, (1000,) * 7 + (535,), 1),
        (64000, (1000,) * 8, 1),
        (32000, (1000, 1000, 465, 0, 0, 0, 0, 0), 1),
    ]
    for node in seq:
        assert ev.eval(*node) == pytest.approx(
            node_frag_bellman(node, t, memo=pymemo), abs=1e-9
        )
