#!/usr/bin/env python
"""Multi-chip scale proof: the 100k-node synthetic stress through the
node-axis-sharded table engine on a virtual CPU mesh (1/2/4/8 devices),
asserting placement equality against the single-device replay and
recording per-event wall + compile/table-init cost per mesh size.

One physical host serves every virtual device, so wall-clock SPEEDUP is
not observable here — what this measures is that the sharded program (a)
stays placement-identical at scale, (b) keeps per-event cost flat as the
mesh grows (the per-event column refresh is local to the owning chip; only
the selectHost argmax all-reduce crosses the mesh), and (c) does not
serialize the [K, N] table init. Real-ICI scaling follows the same program
with real devices (ref scale-out being replaced: the vendored scheduler's
16-way parallelize over nodes, generic_scheduler.go:473-560, and the
harness's xargs --max-procs process fleet).

    python bench_multichip.py                       # 100k nodes, 8k events
    python bench_multichip.py --nodes 20000 --events 2048 --devices 1 2 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=100_000)
    ap.add_argument("--events", type=int, default=8192)
    ap.add_argument("--devices", type=int, nargs="*", default=[1, 2, 4, 8])
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--out", default="MULTICHIP.md")
    ap.add_argument(
        "--engine", choices=["shardmap", "partitioner"], default="shardmap",
        help="shardmap = explicit-collective engine (parallel.shard_engine, "
        "flat us/event); partitioner = XLA-SPMD-partitioned table engine "
        "(parallel.sharding, the round-2 baseline)",
    )
    args = ap.parse_args()
    max_dev = max(args.devices)

    # virtual CPU mesh must be configured before jax initializes; reuse the
    # graft entry's helper (it also overrides a stale pre-set device count)
    import re

    os.environ["XLA_FLAGS"] = (
        re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        ).strip()
        + f" --xla_force_host_platform_device_count={max_dev}"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)

    import jax.numpy as jnp
    import numpy as np

    from bench_scale import synth_cluster, synth_pods
    from tpusim.io.trace import build_events, pods_to_specs, tiebreak_rank
    from tpusim.parallel import (
        make_mesh,
        make_sharded_table_replay,
        pad_nodes,
        shard_state,
    )
    from tpusim.policies import make_policy
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.table_engine import build_pod_types, pad_pod_types
    from tpusim.sim.typical import TypicalPodsConfig

    assert len(jax.devices()) >= max_dev, (
        f"need {max_dev} devices, have {len(jax.devices())}"
    )

    nodes = synth_cluster(args.nodes, args.seed)
    pods = synth_pods(args.events, args.seed + 1)
    cfg = SimulatorConfig(
        policies=(("FGDScore", 1000),),
        gpu_sel_method="FGDScore",
        seed=args.seed,
        report_per_event=False,
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
    )
    sim = Simulator(nodes, cfg)
    sim.set_workload_pods(pods)
    sim.set_typical_pods()

    specs = pods_to_specs(pods)
    ev_kind, ev_pod = build_events(pods)
    ev_kind, ev_pod = jnp.asarray(ev_kind), jnp.asarray(ev_pod)
    types = pad_pod_types(build_pod_types(specs))
    key = jax.random.PRNGKey(args.seed)
    base_rank = jnp.asarray(tiebreak_rank(len(nodes), cfg.seed))
    policies = [(make_policy("FGDScore"), 1000)]

    rows = []
    ref_placed = None
    for n_dev in args.devices:
        mesh = make_mesh(n_dev)
        state, rank = pad_nodes(sim.init_state, base_rank, n_dev)
        state = shard_state(state, mesh)
        if args.engine == "shardmap":
            from tpusim.parallel.shard_engine import make_shardmap_table_replay

            replay = make_shardmap_table_replay(
                policies, mesh, gpu_sel="FGDScore"
            )
        else:
            replay = make_sharded_table_replay(
                policies, mesh, gpu_sel="FGDScore"
            )

        from tpusim.obs import bench as obs_bench

        box = {}

        def run():
            box["out"] = replay(
                state, specs, types, ev_kind, ev_pod, sim.typical, key, rank
            )
            jax.block_until_ready(box["out"].state)

        # shared cold/warm protocol (tpusim.obs.bench): every mesh size
        # compiles its own program, one warm call is the signal
        m = obs_bench.measure_cold_warm(run)
        out, cold, warm = box["out"], m["cold_s"], m["warm_s"]

        placed = np.asarray(out.placed_node)
        n_placed = int((placed >= 0).sum())
        if ref_placed is None:
            ref_mesh = n_dev  # first (smallest) mesh size is the reference
            ref_placed = placed
            equal = True
        else:
            equal = bool(np.array_equal(placed, ref_placed))
        rows.append(
            {
                "devices": n_dev,
                "nodes": args.nodes,
                "events": args.events,
                "placed": n_placed,
                "cold_s": round(cold, 2),
                "warm_s": round(warm, 2),
                "us_per_event": round(1e6 * warm / args.events, 1),
                f"equal_vs_{ref_mesh}dev": equal,
            }
        )
        print(json.dumps(rows[-1]), flush=True)
        assert equal, (
            f"placements diverged: {n_dev}-device vs {ref_mesh}-device mesh"
        )

    engine_desc = (
        "explicit-collective shard_map engine (tpusim.parallel.shard_engine: "
        "local Filter/Score/refresh, 3-scalar selectHost collectives, "
        "owner-local bind)"
        if args.engine == "shardmap"
        else "XLA-SPMD-partitioned table engine (tpusim.parallel.sharding)"
    )
    with open(os.path.join(REPO, args.out), "w") as f:
        f.write(
            "# MULTICHIP — node-axis-sharded table engine at scale\n\n"
            "Generated by `python bench_multichip.py` "
            f"(nodes={args.nodes}, events={args.events}, FGD, "
            f"{engine_desc}, virtual CPU "
            "mesh — one physical host backs all virtual devices, so this "
            "table proves placement equality + flat per-event cost under "
            "sharding, not wall-clock speedup; see bench_multichip.py "
            "docstring).\n\n"
            f"| devices | cold (compile+init) s | warm replay s | us/event | "
            f"placements equal vs {ref_mesh}-device |\n|---|---|---|---|---|\n"
        )
        for r in rows:
            f.write(
                f"| {r['devices']} | {r['cold_s']} | {r['warm_s']} | "
                f"{r['us_per_event']} | {r[f'equal_vs_{ref_mesh}dev']} |\n"
            )
        f.write(
            f"\nplaced = {rows[0]['placed']} / {args.events} on every mesh "
            "size (bit-identical placements and device masks).\n"
        )
        if args.engine == "shardmap":
            r1 = next(
                (r["us_per_event"] for r in rows if r["devices"] == 1), None
            )
            r8 = next(
                (r["us_per_event"] for r in rows if r["devices"] == 8), None
            )
            f.write(
                "\n## Why the curve is flat now\n\n"
                "Round 2's sharded engine re-jitted the table engine with "
                "node-axis in_shardings and let XLA's SPMD partitioner place "
                "the communication; the per-event dynamic gathers/scatters "
                "at the winning node became whole-array movement and "
                "us/event grew 3.5x from 1 to 8 devices (2750.9 -> 9730.9 "
                "at these exact settings). The shard_map engine "
                "(tpusim/parallel/shard_engine.py) writes the collectives "
                "by hand — local Filter/Score/table-refresh, a 3-scalar "
                "selectHost reduction (pmax best score, pmin winner rank, "
                "psum winner node id), owner-local bind with one 8-lane "
                "psum; per-event metrics never touch the loop (the shared "
                "post-pass, tpusim.sim.metrics, reconstructs the report "
                "series from the replicated telemetry) — so the per-event "
                "collective payload is independent of cluster and mesh size"
                + (
                    f" (this run: {r8} us/event at 8 devices vs {r1} at 1, "
                    f"ratio {r8 / r1:.2f})"
                    if r8 and r1
                    else ""
                )
                + ". Run-to-run variance on the shared host is ~20-50%; "
                "the signal is the ratio staying ~1, not the absolute "
                "numbers.\n"
            )
            f.write(
                "\n## Blocked local selectHost (round 6)\n\n"
                "`make_shardmap_table_replay(..., block_size=...)` (driven "
                "by `SimulatorConfig.block_size`, default auto) layers the "
                "blocked table engine's incremental reductions onto each "
                "shard for configs whose policies all use "
                "`normalize: \"none\"` (FGD, DotProd, Packing, Clustering "
                "— including this file's FGD lane): each device keeps "
                "per-(type, block-of-B) summaries of (max total, min "
                "tie-break rank, winner node), refreshed only at the "
                "touched node's block, so the per-event selectHost input "
                "on each device shrinks from nloc node rows to nloc/B "
                "block maxima before the device contributes its scalar to "
                "the collective. The cross-device payload itself was "
                "already N-independent (3 scalars + one 8-lane psum) and "
                "is unchanged; what shrinks is the local reduction feeding "
                "it — the dominant per-event cost at nloc = N/D >= ~10k. "
                "Placements stay bit-identical (the block summaries feed "
                "the same lexicographic (max score, min rank) combine — "
                "sim.step.block_reduce/packed_argmax, shared with the "
                "single-device blocked engine). Normalized policies "
                "(minmax/pwr) keep the flat local path: their per-event "
                "global-extrema pmin/pmax collectives need the full local "
                "rows anyway.\n"
            )
            f.write(
                "\n## Product path (round 5)\n\n"
                "Sharding is a config knob, not a bench-only engine: "
                "`customConfig.mesh: N` in the Simon CR, "
                "`SimulatorConfig.mesh`, or `experiments/run.py --mesh N` "
                "route every replay through this engine on an N-device "
                "mesh (the single-chip tunnel auto-falls back to N virtual "
                "CPU devices via tpusim.virtual_mesh). Verified end to "
                "end: a full sweep-protocol cell (openb default x FGD x "
                "tune 1.3, per-event reports) run with --mesh 8 writes "
                "ALL analysis CSV families byte-identical to the "
                "single-device run on the same backend "
                "(tests/test_mesh_product.py pins the same on the tiny "
                "trace + the Simon-CR knob). Cross-backend runs (virtual "
                "CPU mesh vs real TPU) differ only in the documented f32 "
                "last-ulp report channel; placements are identical "
                "everywhere.\n"
            )
    print(f"[multichip] wrote {args.out}")


if __name__ == "__main__":
    main()
