#!/usr/bin/env python
"""Multi-chip scale proof: the 100k-node synthetic stress through the
node-axis-sharded table engine on a virtual CPU mesh (1/2/4/8 devices),
asserting placement equality against the single-device replay and
recording per-event wall + compile/table-init cost per mesh size.

One physical host serves every virtual device, so wall-clock SPEEDUP is
not observable here — what this measures is that the sharded program (a)
stays placement-identical at scale, (b) keeps per-event cost flat as the
mesh grows (the per-event column refresh is local to the owning chip; only
the selectHost argmax all-reduce crosses the mesh), and (c) does not
serialize the [K, N] table init. Real-ICI scaling follows the same program
with real devices (ref scale-out being replaced: the vendored scheduler's
16-way parallelize over nodes, generic_scheduler.go:473-560, and the
harness's xargs --max-procs process fleet).

    python bench_multichip.py                       # 100k nodes, 8k events
    python bench_multichip.py --nodes 20000 --events 2048 --devices 1 2 4

The 1M-node lane (ISSUE 11): `--scale-lane` measures the
software-pipelined shard commit against the unpipelined body at
nloc ∈ {10k, 100k, 250k} per device, then streams a 1M-node aggregate
replay through the chunked run_chunk surface with buffer donation armed
(events generated chunk-by-chunk, never materialized as one array), and
writes the machine-readable capture `--json-out MULTICHIP_r06.json` the
bench gate advisory-compares. `--fault` additionally runs the aggregate
as a chaos bench (the PR 10 fault lane through the shard engine's
pipelined registers).

    python bench_multichip.py --scale-lane --json-out MULTICHIP_r06.json
    python bench_multichip.py --scale-lane --nodes 1000000 --fault
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def _force_virtual_devices(max_dev: int):
    """Pre-jax-init virtual CPU mesh (the shared tpusim.virtual_mesh
    bootstrap; force=True because this bench is CPU-by-design and must
    get its mesh even on images registering inert accelerator plugin
    factories — it also overrides a stale pre-set device count)."""
    from tpusim.virtual_mesh import force_virtual_cpu_devices

    force_virtual_cpu_devices(max(max_dev, 2), force=True)


def synth_pods_pooled(num_events: int, seed: int, pool: int):
    """synth_pods drawing from only the first `pool` rows of the openb
    pod list: caps the distinct-type count K so the 250k/1M table init
    stays CPU-tractable (the per-event loop cost under test is
    K-independent in the select and O(K) in the refresh either way)."""
    import numpy as np

    from tpusim.io.trace import load_pod_csv

    base = load_pod_csv(
        os.path.join(REPO, "data/csv/openb_pod_list_default.csv")
    )[:pool]
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, len(base), num_events)
    return [
        type(base[0])(
            name=f"sp-{i:07d}",
            cpu_milli=base[int(j)].cpu_milli,
            memory_mib=base[int(j)].memory_mib,
            num_gpu=base[int(j)].num_gpu,
            gpu_milli=base[int(j)].gpu_milli,
            gpu_spec=base[int(j)].gpu_spec,
        )
        for i, j in enumerate(idx)
    ]


def scale_lane(args):
    """The 1M-node lane: pipelined-vs-unpipelined us/event at
    nloc ∈ {10k, 100k, 250k} on a 1-device mesh, then the N-node
    aggregate (nloc = N / --agg-devices per device) streamed through
    run_chunk with donation armed. Placement equality pipelined vs
    unpipelined is asserted on every row."""
    _force_virtual_devices(max(args.agg_devices, 1))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench_scale import synth_cluster
    from tpusim.io.trace import build_events, pods_to_specs, tiebreak_rank
    from tpusim.parallel import make_mesh, pad_nodes, shard_state
    from tpusim.parallel.shard_engine import make_shardmap_table_replay
    from tpusim.policies import make_policy
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.table_engine import build_pod_types, pad_pod_types
    from tpusim.sim.typical import TypicalPodsConfig

    policies = [(make_policy("FGDScore"), 1000)]
    cfg = SimulatorConfig(
        policies=(("FGDScore", 1000),),
        gpu_sel_method="FGDScore",
        seed=args.seed,
        report_per_event=False,
        table_residency=args.pallas_residency,
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
    )
    pods = synth_pods_pooled(args.events, args.seed + 1, args.pod_pool)
    specs = pods_to_specs(pods)
    ev_kind_np, ev_pod_np = build_events(pods)
    ev_kind = jnp.asarray(ev_kind_np)
    ev_pod = jnp.asarray(ev_pod_np)
    types = pad_pod_types(build_pod_types(specs))
    key = jax.random.PRNGKey(args.seed)

    def row_inputs(n_nodes, n_dev):
        nodes = synth_cluster(n_nodes, args.seed)
        sim = Simulator(nodes, cfg)
        sim.set_workload_pods(pods)
        sim.set_typical_pods()
        mesh = make_mesh(n_dev)
        base_rank = jnp.asarray(tiebreak_rank(n_nodes, cfg.seed))
        state, rank = pad_nodes(sim.init_state, base_rank, n_dev)
        state = shard_state(state, mesh)
        return sim, mesh, state, rank

    def measure_scan(replay, sim, state, rank, chunk=0, warm_runs=3):
        """(cold_s, warm_s, placed) of the post-init event scan through
        the DONATED chunk entry — the production shape of the 1M lane
        (ENGINES.md Round 15): without donation every run_chunk call
        pays a defensive whole-carry copy at the jit boundary that
        drowns the per-event signal. The first pass pays the compile;
        warm is the min over `warm_runs` passes (each re-inits, since
        donation consumes the carry; init sits outside the timer)."""
        e = int(ev_kind.shape[0])
        step = chunk or e

        def one_pass():
            carry = replay.init_carry(
                state, specs, types, sim.typical, key, rank
            )
            jax.block_until_ready(jax.tree.leaves(carry))
            t0 = time.perf_counter()
            for a in range(0, e, step):
                carry, _ys = replay.run_chunk_donated(
                    carry, specs, types, ev_kind[a:a + step],
                    ev_pod[a:a + step], sim.typical, rank,
                )
            out = replay.finish(carry)
            jax.block_until_ready(jax.tree.leaves(out))
            return time.perf_counter() - t0, out

        cold, _ = one_pass()
        samples = [one_pass() for _ in range(warm_runs)]
        warm, out = min(samples, key=lambda s: s[0])
        return cold, warm, np.asarray(out[1])

    rows = []
    for nloc in args.nloc:
        sim, mesh, state, rank = row_inputs(nloc, 1)
        res = {"nloc": nloc, "devices": 1, "events": args.events}
        placed = {}
        for pipelined in (True, False):
            replay = make_shardmap_table_replay(
                policies, mesh, gpu_sel="FGDScore", pipelined=pipelined
            )
            cold, warm, pl = measure_scan(replay, sim, state, rank)
            tag = "pipelined" if pipelined else "unpipelined"
            res[f"cold_s_{tag}"] = round(cold, 2)
            res[f"warm_s_{tag}"] = round(warm, 3)
            res[f"us_per_event_{tag}"] = round(1e6 * warm / args.events, 1)
            placed[pipelined] = pl
        res["equal"] = bool(np.array_equal(placed[True], placed[False]))
        res["placed"] = int((placed[True] >= 0).sum())
        res["speedup"] = round(
            res["us_per_event_unpipelined"]
            / max(res["us_per_event_pipelined"], 1e-9), 2,
        )
        rows.append(res)
        print(json.dumps(res), flush=True)
        assert res["equal"], f"pipelined != unpipelined at nloc={nloc}"

    # ---- the aggregate: nodes sharded over the mesh, events STREAMED
    # through the donated chunk entry (generated per chunk, one
    # executable across chunks, the input carry's buffers reused)
    agg = None
    if args.nodes:
        n_dev = args.agg_devices
        sim, mesh, state, rank = row_inputs(args.nodes, n_dev)
        replay = make_shardmap_table_replay(
            policies, mesh, gpu_sel="FGDScore", pipelined=True
        )
        cold, warm, pl = measure_scan(
            replay, sim, state, rank, chunk=args.chunk
        )
        agg = {
            "nodes": args.nodes, "devices": n_dev,
            "nloc": args.nodes // n_dev, "events": args.events,
            "chunk": args.chunk, "donated": True,
            "cold_s": round(cold, 2), "warm_s": round(warm, 3),
            "us_per_event": round(1e6 * warm / args.events, 1),
            "placed": int((pl >= 0).sum()),
        }
        if args.fault:
            # chaos variant: the PR 10 fault lane through the pipelined
            # shard registers at aggregate scale
            from tpusim.sim import fault_lane
            from tpusim.sim.faults import (
                FaultConfig,
                generate_fault_schedule,
            )

            fcfg = FaultConfig(
                mtbf_events=max(args.events // 8, 1),
                mttr_events=max(args.events // 8, 1),
                evict_every_events=max(args.events // 16, 1),
                seed=args.seed, backoff_base=4, backoff_cap=32,
                max_retries=2, queue_capacity=16,
            )
            faults = generate_fault_schedule(
                args.nodes, args.events, fcfg
            )
            plan = fault_lane.compile_fault_plan(
                ev_kind_np, ev_pod_np, faults, fcfg, args.nodes,
                args.events,
            )
            n_pad = state.num_nodes
            ops = fault_lane.FaultOps(
                pos=jnp.asarray(plan.pos), arg=jnp.asarray(plan.arg),
                aux=jnp.asarray(plan.aux), draws=jnp.asarray(plan.draws),
                params=jnp.asarray(plan.params),
                gcnt=jnp.pad(
                    jnp.asarray(sim.init_state.gpu_cnt),
                    (0, n_pad - sim.init_state.num_nodes),
                ),
            )
            fc0 = fault_lane.init_fault_carry(
                args.events, n_pad, plan.capacity
            )
            frep = make_shardmap_table_replay(
                policies, mesh, gpu_sel="FGDScore", faults=True
            )
            ftypes = build_pod_types(specs)  # hoisted out of the timer
            fkind, fidx = jnp.asarray(plan.kind), jnp.asarray(plan.idx)

            def fault_pass():
                t0 = time.perf_counter()
                out = frep(
                    state, specs, ftypes, fkind, fidx,
                    sim.typical, key, rank, fault_ops=ops,
                    fault_carry0=fc0,
                )
                jax.block_until_ready(out.placed_node)
                return time.perf_counter() - t0, out

            fcold, _ = fault_pass()
            fwarm, fout = fault_pass()
            e_m = int(plan.kind.shape[0])
            dm, _, attempts = fault_lane.assemble_disruption(
                plan, fout.fault_ys, fout.fault_carry,
                np.asarray(sim.init_state.gpu_cnt), frag_delta=False,
            )
            agg["fault"] = {
                "merged_events": e_m,
                "cold_s": round(fcold, 2), "warm_s": round(fwarm, 3),
                "us_per_event": round(1e6 * fwarm / e_m, 1),
                "evicted": dm.evicted_pods,
                "rescheduled": dm.rescheduled_pods,
                "dead": dm.unscheduled_after_retries,
                "retries_run": attempts,
            }
        print(json.dumps(agg), flush=True)

    capture = {
        "n": args.round, "rc": 0, "kind": "scale-lane",
        "scale": {
            "backend": jax.default_backend(),
            "devices_virtual": True,
            "events": args.events,
            "pod_pool": args.pod_pool,
            "rows": rows,
            "aggregate": agg,
        },
    }
    if args.json_out:
        with open(os.path.join(REPO, args.json_out), "w") as f:
            json.dump(capture, f, indent=1)
            f.write("\n")
        print(f"[multichip] wrote {args.json_out}")
    return capture


def main():
    ap = argparse.ArgumentParser()
    # default resolves per mode below: 100k for the classic mesh table,
    # 1M for --scale-lane (so the documented one-liner really runs the
    # 1M aggregate instead of silently overwriting the committed capture
    # with a 100k one)
    ap.add_argument("--nodes", type=int, default=None)
    ap.add_argument("--events", type=int, default=8192)
    ap.add_argument("--devices", type=int, nargs="*", default=[1, 2, 4, 8])
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--out", default="MULTICHIP.md")
    ap.add_argument(
        "--engine", choices=["shardmap", "partitioner"], default="shardmap",
        help="shardmap = explicit-collective engine (parallel.shard_engine, "
        "flat us/event); partitioner = XLA-SPMD-partitioned table engine "
        "(parallel.sharding, the round-2 baseline)",
    )
    ap.add_argument(
        "--scale-lane", action="store_true",
        help="the 1M-node lane (ISSUE 11): pipelined-vs-unpipelined "
        "us/event rows at --nloc per device + the --nodes aggregate "
        "streamed through donated chunks; writes --json-out",
    )
    ap.add_argument(
        "--nloc", type=int, nargs="*", default=[10_000, 100_000, 250_000],
        help="scale-lane per-device node counts (1-device mesh rows)",
    )
    ap.add_argument(
        "--agg-devices", type=int, default=4,
        help="scale-lane aggregate mesh width (nloc = --nodes / this)",
    )
    ap.add_argument(
        "--pod-pool", type=int, default=32,
        help="scale-lane distinct-pod-type cap (openb rows sampled)",
    )
    ap.add_argument(
        "--chunk", type=int, default=512,
        help="scale-lane aggregate chunk length (events per donated "
        "run_chunk dispatch)",
    )
    ap.add_argument(
        "--fault", action="store_true",
        help="scale-lane: also run the aggregate as a chaos bench "
        "(fault-lane merged stream through the shard engine)",
    )
    ap.add_argument(
        "--pallas-residency", default="auto", metavar="auto|vmem|hbm",
        help="fused-Pallas table residency for any single-device "
        "reference dispatch this bench makes (SimulatorConfig."
        "table_residency, ENGINES.md Round 19); the shard rows "
        "themselves run the shard_map engine and ignore it — the knob "
        "exists so mixed captures stay comparable with bench_scale's",
    )
    ap.add_argument(
        "--json-out", default="",
        help="scale-lane capture path (e.g. MULTICHIP_r06.json)",
    )
    ap.add_argument(
        "--round", type=int, default=6,
        help="capture round number recorded in --json-out",
    )
    args = ap.parse_args()
    if args.nodes is None:
        args.nodes = 1_000_000 if args.scale_lane else 100_000
    if args.scale_lane:
        scale_lane(args)
        return
    max_dev = max(args.devices)

    # virtual CPU mesh must be configured before jax initializes (also
    # overrides a stale pre-set device count)
    _force_virtual_devices(max_dev)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench_scale import synth_cluster, synth_pods
    from tpusim.io.trace import build_events, pods_to_specs, tiebreak_rank
    from tpusim.parallel import (
        make_mesh,
        make_sharded_table_replay,
        pad_nodes,
        shard_state,
    )
    from tpusim.policies import make_policy
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.table_engine import build_pod_types, pad_pod_types
    from tpusim.sim.typical import TypicalPodsConfig

    assert len(jax.devices()) >= max_dev, (
        f"need {max_dev} devices, have {len(jax.devices())}"
    )

    nodes = synth_cluster(args.nodes, args.seed)
    pods = synth_pods(args.events, args.seed + 1)
    cfg = SimulatorConfig(
        policies=(("FGDScore", 1000),),
        gpu_sel_method="FGDScore",
        seed=args.seed,
        report_per_event=False,
        table_residency=args.pallas_residency,
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
    )
    sim = Simulator(nodes, cfg)
    sim.set_workload_pods(pods)
    sim.set_typical_pods()

    specs = pods_to_specs(pods)
    ev_kind, ev_pod = build_events(pods)
    ev_kind, ev_pod = jnp.asarray(ev_kind), jnp.asarray(ev_pod)
    types = pad_pod_types(build_pod_types(specs))
    key = jax.random.PRNGKey(args.seed)
    base_rank = jnp.asarray(tiebreak_rank(len(nodes), cfg.seed))
    policies = [(make_policy("FGDScore"), 1000)]

    rows = []
    ref_placed = None
    for n_dev in args.devices:
        mesh = make_mesh(n_dev)
        state, rank = pad_nodes(sim.init_state, base_rank, n_dev)
        state = shard_state(state, mesh)
        if args.engine == "shardmap":
            from tpusim.parallel.shard_engine import make_shardmap_table_replay

            replay = make_shardmap_table_replay(
                policies, mesh, gpu_sel="FGDScore"
            )
        else:
            replay = make_sharded_table_replay(
                policies, mesh, gpu_sel="FGDScore"
            )

        from tpusim.obs import bench as obs_bench

        box = {}

        def run():
            box["out"] = replay(
                state, specs, types, ev_kind, ev_pod, sim.typical, key, rank
            )
            jax.block_until_ready(box["out"].state)

        # shared cold/warm protocol (tpusim.obs.bench): every mesh size
        # compiles its own program, one warm call is the signal
        m = obs_bench.measure_cold_warm(run)
        out, cold, warm = box["out"], m["cold_s"], m["warm_s"]

        placed = np.asarray(out.placed_node)
        n_placed = int((placed >= 0).sum())
        if ref_placed is None:
            ref_mesh = n_dev  # first (smallest) mesh size is the reference
            ref_placed = placed
            equal = True
        else:
            equal = bool(np.array_equal(placed, ref_placed))
        rows.append(
            {
                "devices": n_dev,
                "nodes": args.nodes,
                "events": args.events,
                "placed": n_placed,
                "cold_s": round(cold, 2),
                "warm_s": round(warm, 2),
                "us_per_event": round(1e6 * warm / args.events, 1),
                f"equal_vs_{ref_mesh}dev": equal,
            }
        )
        print(json.dumps(rows[-1]), flush=True)
        assert equal, (
            f"placements diverged: {n_dev}-device vs {ref_mesh}-device mesh"
        )

    engine_desc = (
        "explicit-collective shard_map engine (tpusim.parallel.shard_engine: "
        "local Filter/Score/refresh, 3-scalar selectHost collectives, "
        "owner-local bind)"
        if args.engine == "shardmap"
        else "XLA-SPMD-partitioned table engine (tpusim.parallel.sharding)"
    )
    with open(os.path.join(REPO, args.out), "w") as f:
        f.write(
            "# MULTICHIP — node-axis-sharded table engine at scale\n\n"
            "Generated by `python bench_multichip.py` "
            f"(nodes={args.nodes}, events={args.events}, FGD, "
            f"{engine_desc}, virtual CPU "
            "mesh — one physical host backs all virtual devices, so this "
            "table proves placement equality + flat per-event cost under "
            "sharding, not wall-clock speedup; see bench_multichip.py "
            "docstring).\n\n"
            f"| devices | cold (compile+init) s | warm replay s | us/event | "
            f"placements equal vs {ref_mesh}-device |\n|---|---|---|---|---|\n"
        )
        for r in rows:
            f.write(
                f"| {r['devices']} | {r['cold_s']} | {r['warm_s']} | "
                f"{r['us_per_event']} | {r[f'equal_vs_{ref_mesh}dev']} |\n"
            )
        f.write(
            f"\nplaced = {rows[0]['placed']} / {args.events} on every mesh "
            "size (bit-identical placements and device masks).\n"
        )
        if args.engine == "shardmap":
            r1 = next(
                (r["us_per_event"] for r in rows if r["devices"] == 1), None
            )
            r8 = next(
                (r["us_per_event"] for r in rows if r["devices"] == 8), None
            )
            f.write(
                "\n## Why the curve is flat now\n\n"
                "Round 2's sharded engine re-jitted the table engine with "
                "node-axis in_shardings and let XLA's SPMD partitioner place "
                "the communication; the per-event dynamic gathers/scatters "
                "at the winning node became whole-array movement and "
                "us/event grew 3.5x from 1 to 8 devices (2750.9 -> 9730.9 "
                "at these exact settings). The shard_map engine "
                "(tpusim/parallel/shard_engine.py) writes the collectives "
                "by hand — local Filter/Score/table-refresh, a 3-scalar "
                "selectHost reduction (pmax best score, pmin winner rank, "
                "psum winner node id), owner-local bind with one 8-lane "
                "psum; per-event metrics never touch the loop (the shared "
                "post-pass, tpusim.sim.metrics, reconstructs the report "
                "series from the replicated telemetry) — so the per-event "
                "collective payload is independent of cluster and mesh size"
                + (
                    f" (this run: {r8} us/event at 8 devices vs {r1} at 1, "
                    f"ratio {r8 / r1:.2f})"
                    if r8 and r1
                    else ""
                )
                + ". Run-to-run variance on the shared host is ~20-50%; "
                "the signal is the ratio staying ~1, not the absolute "
                "numbers.\n"
            )
            f.write(
                "\n## Blocked local selectHost (round 6)\n\n"
                "`make_shardmap_table_replay(..., block_size=...)` (driven "
                "by `SimulatorConfig.block_size`, default auto) layers the "
                "blocked table engine's incremental reductions onto each "
                "shard for configs whose policies all use "
                "`normalize: \"none\"` (FGD, DotProd, Packing, Clustering "
                "— including this file's FGD lane): each device keeps "
                "per-(type, block-of-B) summaries of (max total, min "
                "tie-break rank, winner node), refreshed only at the "
                "touched node's block, so the per-event selectHost input "
                "on each device shrinks from nloc node rows to nloc/B "
                "block maxima before the device contributes its scalar to "
                "the collective. The cross-device payload itself was "
                "already N-independent (3 scalars + one 8-lane psum) and "
                "is unchanged; what shrinks is the local reduction feeding "
                "it — the dominant per-event cost at nloc = N/D >= ~10k. "
                "Placements stay bit-identical (the block summaries feed "
                "the same lexicographic (max score, min rank) combine — "
                "sim.step.block_reduce/packed_argmax, shared with the "
                "single-device blocked engine). Normalized policies "
                "(minmax/pwr) keep the flat local path: their per-event "
                "global-extrema pmin/pmax collectives need the full local "
                "rows anyway.\n"
            )
            f.write(
                "\n## Product path (round 5)\n\n"
                "Sharding is a config knob, not a bench-only engine: "
                "`customConfig.mesh: N` in the Simon CR, "
                "`SimulatorConfig.mesh`, or `experiments/run.py --mesh N` "
                "route every replay through this engine on an N-device "
                "mesh (the single-chip tunnel auto-falls back to N virtual "
                "CPU devices via tpusim.virtual_mesh). Verified end to "
                "end: a full sweep-protocol cell (openb default x FGD x "
                "tune 1.3, per-event reports) run with --mesh 8 writes "
                "ALL analysis CSV families byte-identical to the "
                "single-device run on the same backend "
                "(tests/test_mesh_product.py pins the same on the tiny "
                "trace + the Simon-CR knob). Cross-backend runs (virtual "
                "CPU mesh vs real TPU) differ only in the documented f32 "
                "last-ulp report channel; placements are identical "
                "everywhere.\n"
            )
    print(f"[multichip] wrote {args.out}")


if __name__ == "__main__":
    main()
