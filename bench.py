#!/usr/bin/env python
"""Headline benchmark: full openb production-trace replay under FGD.

Mirrors the reference's flagship experiment (openb_pod_list_default,
FGD policy, workload tuning ratio 1.3 — experiments/README.md): 1523 nodes /
6212 GPUs, ~10.6k pod placements after tuning. The reference takes ~10 min on
2 vCPU for this replay (≈13.6 placements/sec, BASELINE.md); here the whole
event loop is one compiled lax.scan on the TPU.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "placements/sec", "vs_baseline": N}
plus auxiliary quality numbers (GPU allocation ratio) on stderr.

Methodology (pinned round 5, the ONE protocol behind every throughput
number in BENCH_r*/BENCH_DETAILS/ENGINES.md): stable minimum over
WARM_RUNS (6) warm replays after one compile run — the tunneled chip's
wall clocks vary ±20% run to run, and the minimum estimates the
noise-free device cost; raw samples ship alongside (wall_samples_s).

`--all` additionally measures every sweep policy (the 6 reference-cached
methods + PWR), pinning the sequential path's throughput (RandomScore /
gpu_sel=random cannot use the table engine) and the 16-seed batched
aggregate, writing the rows to BENCH_DETAILS.json (stderr shows them too).
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

from tpusim.obs import bench as obs_bench  # noqa: E402 (path insert above)
from tpusim.obs.bench import WARM_RUNS  # noqa: E402  timing protocol home

# Implied reference throughput: 8152 placements / ~10 min on 2 vCPU
# (BASELINE.md "Implied placement throughput").
BASELINE_PLACEMENTS_PER_SEC = 13.59

# (name, policies, gpu_sel, dim_ext, norm) — the sweep's method configs
# (experiments/generate_run_scripts.py METHODS)
POLICY_ROWS = [
    ("Random", (("RandomScore", 1000),), "random", "merge", "max"),
    ("DotProd", (("DotProductScore", 1000),), "best", "merge", "max"),
    ("GpuClustering", (("GpuClusteringScore", 1000),), "best", "share", "max"),
    ("GpuPacking", (("GpuPackingScore", 1000),), "best", "share", "max"),
    ("BestFit", (("BestFitScore", 1000),), "best", "share", "max"),
    ("FGD", (("FGDScore", 1000),), "FGDScore", "share", "max"),
    ("PWR", (("PWRScore", 1000),), "PWRScore", "share", "max"),
]


def load_trace():
    from tpusim.io.trace import load_node_csv, load_pod_csv

    node_csv = os.path.join(REPO, "data/csv/openb_node_list_gpu_node.csv")
    pod_csv = os.path.join(REPO, "data/csv/openb_pod_list_default.csv")
    return load_node_csv(node_csv), load_pod_csv(pod_csv)


def gpu_alloc_pct(state) -> float:
    import numpy as np

    from tpusim.constants import MILLI

    slot = np.arange(state.gpu_left.shape[1])[None, :] < state.gpu_cnt[:, None]
    milli_used = int(np.where(slot, MILLI - state.gpu_left, 0).sum())
    return 100.0 * milli_used / (int(state.gpu_cnt.sum()) * MILLI)


def measure_policy(nodes, pods, name, policies, gpu_sel, dim_ext, norm,
                   warm_runs=WARM_RUNS, profile=False):
    """One policy's replay throughput + end-state quality (both engines
    where the config allows; the table engine rejects per-event
    randomness). Timing = the shared cold + warm-minimum protocol
    (tpusim.obs.bench.measure). profile=True runs under obs profiling and
    returns the RunTelemetry in the row's `_telemetry` key (the bench
    gate's smoke profile)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpusim.io.trace import build_events, pods_to_specs
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.typical import TypicalPodsConfig

    cfg = SimulatorConfig(
        policies=policies,
        gpu_sel_method=gpu_sel,
        dim_ext_method=dim_ext,
        norm_method=norm,
        tuning_ratio=1.3,
        tuning_seed=42,
        seed=42,
        shuffle_pod=True,
        report_per_event=False,
        profile=profile,
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
    )
    sim = Simulator(nodes, cfg)
    sim.set_workload_pods(pods)
    sim.set_typical_pods()
    trace = sim.prepare_pods()
    specs = pods_to_specs(trace)
    ev_kind, ev_pod = build_events(trace)
    ev_kind, ev_pod = jnp.asarray(ev_kind), jnp.asarray(ev_pod)
    key = jax.random.PRNGKey(cfg.seed)
    box = {}

    def run():
        res = sim.run_events(sim.init_state, specs, ev_kind, ev_pod, key, bucket=1)
        jax.block_until_ready(res.state)
        box["result"] = res

    m = obs_bench.measure(run, warm_runs)
    result, wall = box["result"], m["min_s"]

    events = int(ev_kind.shape[0])
    unscheduled = int(np.asarray(result.ever_failed).sum())
    placements = events - unscheduled
    state = jax.tree.map(np.asarray, result.state)
    row = obs_bench.round_row({
        "policy": name,
        "engine": sim._last_engine,
        "events": events,
        "placements": placements,
        "wall_s": wall,
        "wall_samples_s": m["samples_s"],
        "placements_per_sec": round(placements / wall, 1),
        "gpu_alloc_pct": round(gpu_alloc_pct(state), 2),
        "compile_first_s": round(m["first_s"], 1),
    })
    if profile:
        row["_telemetry"] = sim.run_telemetry()
    return row


def measure_batched(nodes, pods, seeds=16, report=False):
    """Aggregate throughput of the seed-batched vmapped replay (FGD config;
    see ENGINES.md) — the sweep's execution mode. report=True measures the
    full-report configuration (replay + the vectorized metrics post-pass),
    i.e. the device phase of the artifact protocol's seed groups."""
    import jax
    import numpy as np

    from tpusim.sim.driver import (
        Simulator,
        SimulatorConfig,
        schedule_pods_batch,
    )
    from tpusim.sim.typical import TypicalPodsConfig

    def mk(seed):
        cfg = SimulatorConfig(
            policies=(("FGDScore", 1000),),
            gpu_sel_method="FGDScore",
            tuning_ratio=1.3,
            tuning_seed=seed,
            seed=seed,
            shuffle_pod=True,
            report_per_event=report,
            typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
        )
        sim = Simulator(nodes, cfg)
        sim.set_workload_pods(pods)
        return sim

    sims = [mk(42 + s) for s in range(seeds)]
    pods_lists = [s.prepare_pods() for s in sims]
    box = {}
    dev_walls = []

    def run():
        box["results"] = schedule_pods_batch(sims, pods_lists)
        dev_walls.append(sims[0]._last_batch_device_s)

    # same shared cold + stable-minimum protocol as measure_policy; the
    # warm samples here are the DEVICE phase (dispatch + fetch) — the
    # like-for-like number against a single run_events call
    m = obs_bench.measure(run, WARM_RUNS)
    results = box["results"]
    warm_dev = dev_walls[1:]  # drop the compile run's sample
    device_wall = min(warm_dev)
    placements = sum(
        r.events - len(r.unscheduled_pods) for r in results
    )
    return obs_bench.round_row({
        "policy": "FGD",
        "engine": f"table, {seeds}-seed vmap batch"
        + (" + report post-pass" if report else ""),
        "events": sum(r.events for r in results),
        "placements": placements,
        "wall_s": device_wall,
        "wall_samples_s": warm_dev,
        "wall_incl_host_prep_s": m["min_s"],
        "placements_per_sec": round(placements / device_wall, 1),
        "gpu_alloc_pct": round(
            float(np.mean([gpu_alloc_pct(r.state) for r in results])), 2
        ),
    })


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--all", action="store_true",
        help="per-policy + batched rows -> BENCH_DETAILS.json",
    )
    args = ap.parse_args()
    nodes, pods = load_trace()

    # headline: exact flags of the reference's 1020-experiment protocol
    # (FGD row): -FGD 1000 -gpusel FGD -dimext share -norm max -tune 1.3
    # -tuneseed 42 --shuffle-pod=true
    head = measure_policy(
        nodes, pods, *next(r for r in POLICY_ROWS if r[0] == "FGD")
    )
    print(
        f"[bench] events={head['events']} placed={head['placements']} "
        f"wall={head['wall_s']:.2f}s "
        f"(first incl. compile {head['compile_first_s']:.1f}s) "
        f"gpu_alloc={head['gpu_alloc_pct']:.2f}% ",
        file=sys.stderr,
    )

    if args.all:
        rows = []
        for name, policies, gpu_sel, dim_ext, norm in POLICY_ROWS:
            row = (
                head
                if name == "FGD"
                else measure_policy(
                    nodes, pods, name, policies, gpu_sel, dim_ext, norm
                )
            )
            rows.append(row)
            print(f"[bench-all] {json.dumps(row)}", file=sys.stderr)
        rows.append(measure_batched(nodes, pods))
        print(f"[bench-all] {json.dumps(rows[-1])}", file=sys.stderr)
        rows.append(measure_batched(nodes, pods, report=True))
        print(f"[bench-all] {json.dumps(rows[-1])}", file=sys.stderr)
        obs_bench.write_json(
            os.path.join(REPO, "BENCH_DETAILS.json"),
            {
                "config": "openb_pod_list_default, tune 1.3, seed 42, "
                "warm steady-state on one TPU chip",
                "baseline_placements_per_sec": BASELINE_PLACEMENTS_PER_SEC,
                "rows": rows,
            },
        )

    print(
        json.dumps(
            {
                "metric": "openb default-trace FGD replay throughput (tune 1.3)",
                "value": head["placements_per_sec"],
                "unit": "placements/sec",
                "vs_baseline": round(
                    head["placements_per_sec"] / BASELINE_PLACEMENTS_PER_SEC, 1
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
