#!/usr/bin/env python
"""Headline benchmark: full openb production-trace replay under FGD.

Mirrors the reference's flagship experiment (openb_pod_list_default,
FGD policy, workload tuning ratio 1.3 — experiments/README.md): 1523 nodes /
6212 GPUs, ~10.6k pod placements after tuning. The reference takes ~10 min on
2 vCPU for this replay (≈13.6 placements/sec, BASELINE.md); here the whole
event loop is one compiled lax.scan on the TPU.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "placements/sec", "vs_baseline": N}
plus auxiliary quality numbers (GPU allocation ratio) on stderr.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# Implied reference throughput: 8152 placements / ~10 min on 2 vCPU
# (BASELINE.md "Implied placement throughput").
BASELINE_PLACEMENTS_PER_SEC = 13.59


def load_trace():
    from tpusim.io.trace import load_node_csv, load_pod_csv

    node_csv = os.path.join(REPO, "data/csv/openb_node_list_gpu_node.csv")
    pod_csv = os.path.join(REPO, "data/csv/openb_pod_list_default.csv")
    return load_node_csv(node_csv), load_pod_csv(pod_csv)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpusim.constants import MILLI
    from tpusim.io.trace import build_events, pods_to_specs
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.typical import TypicalPodsConfig

    nodes, pods = load_trace()
    # exact flags of the reference's 1020-experiment protocol (FGD row):
    # -FGD 1000 -gpusel FGD -dimext share -norm max -tune 1.3 -tuneseed 42
    # --shuffle-pod=true (experiments/run_scripts/generate_run_scripts.py)
    cfg = SimulatorConfig(
        policies=(("FGDScore", 1000),),
        gpu_sel_method="FGDScore",
        tuning_ratio=1.3,
        tuning_seed=42,
        seed=42,
        shuffle_pod=True,
        report_per_event=False,
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
    )
    sim = Simulator(nodes, cfg)
    sim.set_workload_pods(pods)
    sim.set_typical_pods()
    trace = sim.prepare_pods()

    specs = pods_to_specs(trace)
    ev_kind, ev_pod = build_events(trace)
    ev_kind, ev_pod = jnp.asarray(ev_kind), jnp.asarray(ev_pod)
    key = jax.random.PRNGKey(cfg.seed)

    def run():
        # auto-selects the incremental score-table engine (exact-equivalent
        # to the sequential oracle; tests/test_table_engine.py). bucket=1:
        # a single-config benchmark needs no sweep shape-bucketing padding.
        res = sim.run_events(sim.init_state, specs, ev_kind, ev_pod, key, bucket=1)
        jax.block_until_ready(res.state)
        return res

    t0 = time.perf_counter()
    result = run()  # compile + first replay
    compile_and_first = time.perf_counter() - t0

    t0 = time.perf_counter()
    result = run()  # steady-state
    wall = time.perf_counter() - t0

    events = int(ev_kind.shape[0])
    unscheduled = int(np.asarray(result.ever_failed).sum())
    # successful placements only — at tune 1.3 the cluster saturates and a
    # chunk of the tuned events are (correctly) rejected
    placements = events - unscheduled
    throughput = placements / wall

    # Quality cross-check: end-state GPU allocation ratio (the reference's
    # headline metric; FGD @ tune 1.3 reaches ~95.3% MilliGpu, BASELINE.md).
    state = jax.tree.map(np.asarray, result.state)
    slot = np.arange(state.gpu_left.shape[1])[None, :] < state.gpu_cnt[:, None]
    milli_used = int(np.where(slot, MILLI - state.gpu_left, 0).sum())
    milli_cap = int(state.gpu_cnt.sum()) * MILLI
    print(
        f"[bench] events={events} placed={placements} wall={wall:.2f}s "
        f"(first incl. compile {compile_and_first:.1f}s) "
        f"gpu_alloc={100.0 * milli_used / milli_cap:.2f}% "
        f"unscheduled={unscheduled}",
        file=sys.stderr,
    )

    print(
        json.dumps(
            {
                "metric": "openb default-trace FGD replay throughput (tune 1.3)",
                "value": round(throughput, 1),
                "unit": "placements/sec",
                "vs_baseline": round(throughput / BASELINE_PLACEMENTS_PER_SEC, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
